#!/bin/bash
# Probe the axon TPU relay every PERIOD seconds; on a healthy probe, run the
# full bench capture grid + XPlane profile captures. If the relay flaps and
# the capture window produces no healthy rows, return to the probe loop —
# exit only once at least one error-free on-chip row has been logged.
# Healthy = a tiny jitted computation completes with a host read (through the
# relay, only a host read proves remote execution finished).
# Usage: scripts/relay_watch.sh [period_sec] [probe_timeout_sec]
set -u
cd "$(dirname "$0")/.."
PERIOD=${1:-180}
PROBE_TIMEOUT=${2:-150}
LOG=scripts/relay_health.log

probe() {
    local out rc
    out=$(timeout "$PROBE_TIMEOUT" python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jax.jit(lambda a: (a @ a).sum())(jnp.ones((128, 128)))
print('HEALTHY', d.platform, float(x))
" 2>&1)
    rc=$?
    if echo "$out" | grep -q HEALTHY; then return 0; fi
    # rc=124 -> relay timeout (expected outage); anything else is an
    # environment problem worth surfacing verbatim
    if [ "$rc" -ne 124 ]; then
        echo "probe rc=$rc: $(echo "$out" | tail -2 | tr '\n' ' ')" >> "$LOG"
    fi
    return 1
}

healthy_rows_since() {
    # count error-free rows appended to bench_log.jsonl after line $1
    python - "$1" <<'PYEOF'
import json, sys
n = int(sys.argv[1])
rows = open("scripts/bench_log.jsonl").read().splitlines()[n:]
ok = 0
for line in rows:
    try:
        r = json.loads(line).get("rec") or {}
    except Exception:
        continue
    if r.get("value") and not r.get("error"):
        ok += 1
print(ok)
PYEOF
}

echo "watch start $(date -u +%FT%TZ) period=${PERIOD}s probe_timeout=${PROBE_TIMEOUT}s" >> "$LOG"
while true; do
    if probe; then
        echo "HEALTHY $(date -u +%FT%TZ) — capturing full grid" >> "$LOG"
        before=$(wc -l < scripts/bench_log.jsonl)
        # DL4J_FROM_WATCHER stops bench_capture.sh re-arming a second watcher
        DL4J_FROM_WATCHER=1 bash scripts/bench_capture.sh full \
            2>> scripts/capture_r5.log
        ok=$(healthy_rows_since "$before")
        if [ "${ok:-0}" -gt 0 ]; then
            mkdir -p scripts/profiles
            for m in resnet50 transformer; do
                timeout 600 python scripts/profile_flagship.py --model "$m" \
                    >> scripts/capture_r5.log 2>&1
            done
            echo "CAPTURED $(date -u +%FT%TZ) healthy_rows=$ok" >> "$LOG"
            exit 0
        fi
        echo "FLAPPED $(date -u +%FT%TZ) — grid ran but 0 healthy rows; rearming" >> "$LOG"
    else
        echo "down $(date -u +%FT%TZ)" >> "$LOG"
    fi
    sleep "$PERIOD"
done
