"""Measure the GPipe bubble fraction of parallel/pipeline.py.

Round-4 verdict item 4: "measure bubble fraction at M in {4,8,16}". The
schedule runs S + M - 1 ticks for M microbatches, so the idle ("bubble")
fraction is (S-1)/(S+M-1); this script measures it as wall-clock per
microbatch vs the M -> inf asymptote on the virtual 8-device CPU mesh
(the same SPMD program that runs over ICI on hardware).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python scripts/pipeline_bubble.py
Prints one JSON line per M with measured_bubble vs theoretical.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import TransformerBlock
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    PipelineParallel, stack_block_params)


def main():
    S = len(jax.devices())
    mesh = build_mesh({"stage": S})
    F, T, mb = 128, 64, 4
    block = TransformerBlock(n_in=F, n_out=F, n_heads=4, causal=True,
                             activation="identity")
    params = [block.init_params(k, InputType.recurrent(F, T))
              for k in jax.random.split(jax.random.PRNGKey(0), S)]
    stacked = stack_block_params(params)

    results = []
    for M in (4, 8, 16, 32):
        pipe = PipelineParallel(
            mesh, lambda p, x: block.apply(p, {}, x, train=False, rng=None)[0],
            n_blocks=S, n_microbatches=M)
        x = jax.random.normal(jax.random.PRNGKey(1), (M * mb, T, F),
                              jnp.float32)
        fn = jax.jit(pipe)
        fn(stacked, x).block_until_ready()          # compile
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(stacked, x)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / n
        results.append({"M": M, "ticks": S + M - 1,
                        "sec_per_microbatch": dt / M,
                        "theoretical_bubble": round((S - 1) / (S + M - 1), 4)})

    # measured bubble: per-microbatch time inflates by ticks/M over the
    # asymptote; use the largest M as the asymptote estimate
    base = results[-1]["sec_per_microbatch"] / (results[-1]["ticks"]
                                                / results[-1]["M"])
    for r in results:
        r["measured_bubble"] = round(
            max(0.0, 1.0 - base / r["sec_per_microbatch"]), 4)
        print(json.dumps(r))


if __name__ == "__main__":
    main()
