"""Capture + summarize an XPlane trace of a flagship K-step training program.

Thin CLI over the framework's trace engine: capture goes through the
process-global ``TraceSession`` (deeplearning4j_tpu/observability/profiler.py
— single locked owner of ``jax.profiler``), parsing/attribution through the
stdlib XPlane parser (observability/xplane.py). This script's only jobs are
(1) the exact-program guarantee — build the SAME (jitted fn, args)
bench.py times, via ``bench.flagship_setup`` + the same multistep builders
and donation — and (2) argument plumbing.

Usage (on the TPU host / through the relay):
    python scripts/profile_flagship.py --model resnet50 --batch 128 --ksteps 8
    python scripts/profile_flagship.py --model transformer --bf16-act
The raw trace stays in --logdir (default scripts/profiles/<model>/) for
TensorBoard/xprof; the printed summary (also written as attribution.json
next to the trace) is self-contained.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_program(model: str, batch: int, ksteps: int):
    """The same (jitted fn, args) bench.py times for this config — model,
    data, and jit construction come from bench.flagship_setup and the same
    make_*_multistep_train_step + donation, so the profiled program IS the
    benchmarked one."""
    import jax
    import jax.numpy as jnp

    from bench import flagship_setup

    conf, xs, ys, graph = flagship_setup(model, batch, ksteps)
    if graph:
        from deeplearning4j_tpu.nn.graph_network import (
            ComputationGraph, make_graph_multistep_train_step)
        net = ComputationGraph(conf).init()
        multi = jax.jit(make_graph_multistep_train_step(conf),
                        donate_argnums=(0, 1, 2))
    else:
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, make_multistep_train_step)
        net = MultiLayerNetwork(conf).init()
        multi = jax.jit(make_multistep_train_step(conf),
                        donate_argnums=(0, 1, 2))
    args = (net.params_list, net.state_list, net.updater_state, xs, ys,
            jax.random.PRNGKey(0), jnp.int32(0))
    return multi, args


def capture(model: str, batch: int, ksteps: int, logdir: str,
            warmup: int = 2, traced_dispatches: int = 2) -> str:
    import jax

    from deeplearning4j_tpu.observability.profiler import global_trace_session

    fn, args = build_program(model, batch, ksteps)
    params, states, upd = args[0], args[1], args[2]
    rest = args[3:]
    t0 = time.time()
    for _ in range(warmup):
        params, states, upd, loss = fn(params, states, upd, *rest)
    _sync = float(np.asarray(jax.tree_util.tree_leaves(loss)[0]).ravel()[-1])
    print(f"warmup done ({time.time() - t0:.1f}s, loss={_sync:.4f}); tracing...",
          file=sys.stderr)
    session = global_trace_session()
    if session.start("script", logdir=logdir) is None:
        raise SystemExit("trace engine busy: another capture owns the "
                         "process-global profiler")
    for _ in range(traced_dispatches):
        params, states, upd, loss = fn(params, states, upd, *rest)
    float(np.asarray(jax.tree_util.tree_leaves(loss)[0]).ravel()[-1])
    session.stop(summarize=False)  # main() prints the summary itself
    return logdir


def summarize(logdir: str, top: int = 25) -> dict:
    """Per-op self-time table of the newest trace under ``logdir`` (the
    engine's stdlib parser; kept as a function so existing callers and
    --summarize-only share one path)."""
    from deeplearning4j_tpu.observability.xplane import summarize as _summ

    return _summ(logdir, top=top)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "transformer", "moe", "lenet"])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ksteps", type=int, default=8)
    ap.add_argument("--logdir", default=None)
    ap.add_argument("--bf16-act", action="store_true")
    ap.add_argument("--summarize-only", metavar="DIR",
                    help="skip capture; just parse an existing trace dir")
    args = ap.parse_args()

    if args.summarize_only:
        print(json.dumps(summarize(args.summarize_only), indent=1))
        return

    # same dtype setup as bench.py's default / --bf16-act modes
    from deeplearning4j_tpu.common import bf16_matmul_policy, full_bf16_policy
    (full_bf16_policy if args.bf16_act else bf16_matmul_policy)()
    batch = args.batch or {"resnet50": 128, "transformer": 16,
                           "moe": 16, "lenet": 128}[args.model]
    logdir = args.logdir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "profiles", args.model)
    capture(args.model, batch, args.ksteps, logdir)
    print(json.dumps(summarize(logdir), indent=1))


if __name__ == "__main__":
    main()
