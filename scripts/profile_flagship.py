"""Capture + summarize an XPlane trace of a flagship K-step training program.

The round-3 verdict's top perf item: ResNet-50 runs at 21.4% MFU and nobody
knows where the other 78% goes. This script answers that the way the
reference's cuDNN work was guided by nvprof (CudnnConvolutionHelper.java:49):
run the EXACT program bench.py times (same model builders, same K-step
make_*_multistep_train_step, same donated buffers), wrap two dispatches in a
jax.profiler trace, and print the top self-time ops / category split parsed
from the XPlane artifact.

Usage (on the TPU host / through the relay):
    python scripts/profile_flagship.py --model resnet50 --batch 128 --ksteps 8
    python scripts/profile_flagship.py --model transformer --bf16-act
The raw trace stays in --logdir (default scripts/profiles/<model>/) for
TensorBoard/xprof; the printed summary is self-contained.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_program(model: str, batch: int, ksteps: int):
    """The same (jitted fn, args) bench.py times for this config — model,
    data, and jit construction come from bench.flagship_setup and the same
    make_*_multistep_train_step + donation, so the profiled program IS the
    benchmarked one."""
    import jax
    import jax.numpy as jnp

    from bench import flagship_setup

    conf, xs, ys, graph = flagship_setup(model, batch, ksteps)
    if graph:
        from deeplearning4j_tpu.nn.graph_network import (
            ComputationGraph, make_graph_multistep_train_step)
        net = ComputationGraph(conf).init()
        multi = jax.jit(make_graph_multistep_train_step(conf),
                        donate_argnums=(0, 1, 2))
    else:
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, make_multistep_train_step)
        net = MultiLayerNetwork(conf).init()
        multi = jax.jit(make_multistep_train_step(conf),
                        donate_argnums=(0, 1, 2))
    args = (net.params_list, net.state_list, net.updater_state, xs, ys,
            jax.random.PRNGKey(0), jnp.int32(0))
    return multi, args


def capture(model: str, batch: int, ksteps: int, logdir: str,
            warmup: int = 2, traced_dispatches: int = 2) -> str:
    import jax

    fn, args = build_program(model, batch, ksteps)
    params, states, upd = args[0], args[1], args[2]
    rest = args[3:]
    t0 = time.time()
    for _ in range(warmup):
        params, states, upd, loss = fn(params, states, upd, *rest)
    _sync = float(np.asarray(jax.tree_util.tree_leaves(loss)[0]).ravel()[-1])
    print(f"warmup done ({time.time() - t0:.1f}s, loss={_sync:.4f}); tracing...",
          file=sys.stderr)
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    for _ in range(traced_dispatches):
        params, states, upd, loss = fn(params, states, upd, *rest)
    float(np.asarray(jax.tree_util.tree_leaves(loss)[0]).ravel()[-1])
    jax.profiler.stop_trace()
    return logdir


def summarize(logdir: str, top: int = 25) -> dict:
    """Parse the xplane.pb into a per-op self-time table (device planes)."""
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        return {"error": f"no xplane.pb under {logdir}"}
    from jax.profiler import ProfileData

    xspace = ProfileData.from_file(paths[-1])
    plane_names = [p.name for p in xspace.planes]
    out = {"trace": paths[-1], "planes": plane_names}
    # device planes only ("/device:TPU:0" etc.); fall back to host planes so
    # the pipeline still summarizes something on CPU-only smoke runs
    device = [p for p in xspace.planes
              if any(t in p.name.lower() for t in ("tpu", "gpu", "device"))]
    planes = device or list(xspace.planes)
    out["summarized_planes"] = [p.name for p in planes]
    import re

    def opcode(nm: str) -> str:
        """The defining HLO opcode of '%name = type opcode(args)'. Bucketing
        must use THIS, not substring search over the whole HLO string —
        operand text routinely contains 'transpose'/'reshape', which round
        4's parser misread as ~38%% 'datamovement' on every model."""
        m = re.search(r"=\s*(?:\([^=]*?\)\s*|\S+\s+)?([a-z][a-z0-9\-_.]*)\(",
                      nm)
        return m.group(1) if m else nm.split(".")[0].lstrip("%")

    op_time: dict = {}
    total_ns = 0
    for plane in planes:
        lines = list(plane.lines)
        # device planes carry container lines ("XLA Modules", "Steps",
        # "Framework Name Scope") spanning the same wall time as the per-op
        # line — summing every line double-counts. Keep exactly the XLA
        # per-op line when present.
        op_lines = [l for l in lines
                    if (l.name or "").strip().lower() in ("xla ops", "ops")]
        for line in (op_lines or lines):
            for ev in line.events:
                nm = ev.name
                # control-flow wrappers (the K-step scan loop) span their
                # whole body and would double-count every inner op
                if opcode(nm) in ("while", "conditional", "call"):
                    continue
                dur = int(ev.duration_ns)
                op_time[nm] = op_time.get(nm, 0) + dur
                total_ns += dur
    ranked = sorted(op_time.items(), key=lambda kv: -kv[1])[:top]
    out["total_device_ns"] = total_ns
    out["top_ops"] = [
        {"op": k, "ns": v,
         "pct": round(100.0 * v / total_ns, 2) if total_ns else 0.0}
        for k, v in ranked]

    def bucket(nm: str) -> str:
        op = opcode(nm)
        # fusions: classify by the name prefix XLA gives them (it encodes
        # the fused ops: transpose_..., convert_reduce_..., maximum_add_...)
        label = nm.lstrip("%").split(" ")[0].split(".")[0].lower()
        if "conv" in op or label.startswith("convolution"):
            return "conv"
        if op in ("dot", "custom-call") or "matmul" in label:
            return "matmul/custom"
        if any(t in op for t in ("all-reduce", "all-gather", "collective",
                                 "reduce-scatter", "permute")):
            return "collective"
        if op in ("copy", "transpose", "reshape", "bitcast",
                  "dynamic-slice", "dynamic-update-slice") \
                or label.startswith(("copy", "transpose", "bitcast")):
            return "datamovement"
        if op == "fusion":
            # TPU traces do not expose fusion bodies; the big kOutput
            # fusions CONTAIN the convolutions/matmuls plus their
            # elementwise epilogues, so this bucket is "compute", not
            # "elementwise overhead"
            if label.startswith(("convert_reduce", "multiply_reduce",
                                 "reduce")):
                return "fusion:reduce"
            return "fusion:compute"
        return op

    cats: dict = {}
    for k, v in op_time.items():
        cats[bucket(k)] = cats.get(bucket(k), 0) + v
    ranked_cats = sorted(cats.items(), key=lambda kv: -kv[1])
    head, tail = ranked_cats[:11], ranked_cats[11:]
    if tail:  # roll the long tail up so the split still sums to ~100%
        head.append((f"other({len(tail)} buckets)",
                     sum(v for _, v in tail)))
    out["categories_pct"] = {
        k: round(100.0 * v / total_ns, 2) if total_ns else 0.0
        for k, v in head}
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet50", "transformer", "moe", "lenet"])
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--ksteps", type=int, default=8)
    ap.add_argument("--logdir", default=None)
    ap.add_argument("--bf16-act", action="store_true")
    ap.add_argument("--summarize-only", metavar="DIR",
                    help="skip capture; just parse an existing trace dir")
    args = ap.parse_args()

    if args.summarize_only:
        print(json.dumps(summarize(args.summarize_only), indent=1))
        return

    # same dtype setup as bench.py's default / --bf16-act modes
    from deeplearning4j_tpu.common import bf16_matmul_policy, full_bf16_policy
    (full_bf16_policy if args.bf16_act else bf16_matmul_policy)()
    batch = args.batch or {"resnet50": 128, "transformer": 16,
                           "moe": 16, "lenet": 128}[args.model]
    logdir = args.logdir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "profiles", args.model)
    capture(args.model, batch, args.ksteps, logdir)
    print(json.dumps(summarize(logdir), indent=1))


if __name__ == "__main__":
    main()
