#!/usr/bin/env python
"""Load-test the serving engine: open/closed-loop QPS sweep, p50/p99.

Two modes:

- **Self-contained A/B** (default, no --port): builds a small MLP,
  serves it unbatched vs micro-batched at the same offered QPS through
  the real HTTP stack, prints the A/B record, and appends it as JSONL to
  --record (default scripts/serve_load.jsonl, next to bench_log). This
  is the same harness `python bench.py --model serve` wraps; run it here
  when you want the raw record without the bench driver's retry/JSON
  envelope.

      python scripts/load_test.py --qps 400 --duration 3 --max-batch 32

- **Target an already-running InferenceServer** (--port): sweep offered
  QPS open-loop (honest about saturation: the client never slows down,
  so overload shows as latency growth and 429s), or measure closed-loop
  peak throughput with --closed. One JSON line per sweep point.

      python scripts/load_test.py --port 8099 --model mlp \
          --shape 1,16 --sweep 50,100,200,400 --duration 2
      python scripts/load_test.py --port 8099 --model mlp \
          --shape 1,16 --closed --workers 16 --requests 200

- **Decode A/B** (--decode): builds a char-RNN LSTM and runs the
  token-streaming A/B — iteration-level continuous batching vs static
  request-level batching at equal offered sessions/sec, plus int8 vs
  dense decode. PASS requires >= 1.5x tokens/sec, TTFT p99 no worse,
  and recompiles == bucket count in every phase.

      python scripts/load_test.py --decode --slots 8 --sessions 64
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _example(shape_csv: str):
    import numpy as np
    shape = tuple(int(s) for s in shape_csv.split(","))
    return np.random.default_rng(0).normal(size=shape).astype(np.float32)


def _target_mode(args) -> int:
    from deeplearning4j_tpu.keras_server.loadgen import (run_closed_loop,
                                                         run_open_loop)
    example = _example(args.shape)
    if args.closed:
        res = run_closed_loop(args.port, args.model, example,
                              workers=args.workers,
                              requests_per_worker=args.requests,
                              host=args.host)
        print(json.dumps(res))
        return 0
    for qps in (float(q) for q in args.sweep.split(",")):
        res = run_open_loop(args.port, args.model, example, qps=qps,
                            duration_s=args.duration, workers=args.workers,
                            host=args.host)
        print(json.dumps(res), flush=True)
    return 0


def _ab_mode(args) -> int:
    import numpy as np
    from deeplearning4j_tpu.keras_server.loadgen import run_ab
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    n_in, hidden = args.n_in, args.hidden
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("adam")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_in=hidden, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_in=hidden, n_out=10, loss="mcxent",
                               activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    example = np.random.default_rng(0).normal(
        size=(1, n_in)).astype(np.float32)
    rec = run_ab(net, model="load_test_mlp", qps=args.qps,
                 duration_s=args.duration, max_batch=args.max_batch,
                 max_latency_s=args.max_latency_ms / 1e3,
                 max_queue=args.max_queue, example=example,
                 workers=args.workers, record_path=args.record)
    print(json.dumps(rec, indent=2))
    ok = (rec["batched_speedup"] > 1.0 and rec["p99_improvement"] > 1.0
          and rec["batched"]["recompiles"] == rec["batched"]["bucket_count"])
    print(f"# batched_speedup={rec['batched_speedup']}x "
          f"p99_improvement={rec['p99_improvement']}x "
          f"recompiles={rec['batched']['recompiles']} "
          f"buckets={rec['batched']['bucket_count']} -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


def _decode_mode(args) -> int:
    from deeplearning4j_tpu.keras_server.loadgen import run_decode_ab
    from deeplearning4j_tpu.models.char_rnn import char_rnn_lstm
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(
        char_rnn_lstm(args.vocab, hidden=args.hidden, layers=2)).init()
    rec = run_decode_ab(net, model="load_test_char_rnn", slots=args.slots,
                        n_sessions=args.sessions,
                        max_new_tokens=args.max_new_tokens,
                        record_path=args.record)
    print(json.dumps(rec, indent=2))
    drift = rec["int8_vs_dense"]
    ok = (rec["tokens_per_sec_ratio"] >= 1.5
          and rec["ttft_p99_ratio"] >= 1.0
          and all(rec[ph]["recompiles"] == rec[ph]["bucket_count"]
                  for ph in ("continuous", "static", "int8"))
          and drift["mean_prob_drift"] <= 2e-2
          and drift["top1_agreement"] >= 0.9)
    print(f"# tokens_per_sec_ratio={rec['tokens_per_sec_ratio']}x "
          f"ttft_p99_ratio={rec['ttft_p99_ratio']}x "
          f"int8_drift={drift['mean_prob_drift']} "
          f"int8_top1={drift['top1_agreement']} -> "
          f"{'PASS' if ok else 'FAIL'}", file=sys.stderr)
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--port", type=int, default=None,
                    help="target an already-running InferenceServer "
                         "(default: self-contained A/B)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--model", default="model",
                    help="registered model name on the target server")
    ap.add_argument("--shape", default="1,64",
                    help="request input shape, comma-separated (target mode)")
    ap.add_argument("--sweep", default="50,100,200,400",
                    help="comma-separated offered-QPS sweep (target mode)")
    ap.add_argument("--closed", action="store_true",
                    help="closed-loop peak-throughput probe instead of the "
                         "open-loop sweep (target mode)")
    ap.add_argument("--requests", type=int, default=200,
                    help="closed-loop requests per worker")
    ap.add_argument("--qps", type=float, default=400.0,
                    help="offered QPS for the A/B (self-contained mode)")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per phase / sweep point")
    ap.add_argument("--workers", type=int, default=32)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-latency-ms", type=float, default=4.0,
                    help="micro-batcher coalescing wait (A/B batched phase)")
    ap.add_argument("--max-queue", type=int, default=2048)
    ap.add_argument("--hidden", type=int, default=128,
                    help="A/B model hidden width")
    ap.add_argument("--n-in", type=int, default=16,
                    help="A/B model input width (also the request payload "
                         "size — serving is wire-cost sensitive)")
    ap.add_argument("--decode", action="store_true",
                    help="token-streaming decode A/B: continuous vs static "
                         "batching, int8 vs dense")
    ap.add_argument("--slots", type=int, default=8,
                    help="decode slot capacity (both A/B phases)")
    ap.add_argument("--sessions", type=int, default=256,
                    help="decode A/B session count (longer run, less noise)")
    ap.add_argument("--max-new-tokens", type=int, default=24,
                    help="decode A/B per-session token budget ceiling")
    ap.add_argument("--vocab", type=int, default=32,
                    help="decode A/B char-RNN vocabulary size")
    ap.add_argument("--record", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "serve_load.jsonl"),
        help="JSONL record path (A/B mode); '' disables")
    args = ap.parse_args()
    if args.port is not None:
        return _target_mode(args)
    if args.decode:
        return _decode_mode(args)
    return _ab_mode(args)


if __name__ == "__main__":
    sys.exit(main())
