#!/usr/bin/env bash
# graftlint gate: the package must be lint-clean, and the suppression
# inventory must match the committed baseline (scripts/lint_baseline.json) —
# a new `# lint: ...-ok` marker is a reviewable event, not ambient noise.
#
#   ./scripts/lint_gate.sh            # gate (exit 1 on violations or drift)
#   ./scripts/lint_gate.sh --update   # regenerate the baseline after review
#
# The baseline keys suppressions by (rule, path, reason, rule_version) —
# line-insensitive, so unrelated edits that shift code don't churn the
# gate, but keyed to the rule's implementation hash: editing a rule
# invalidates every suppression written against the old behaviour, so a
# changed check forces its silenced findings back into review.
set -euo pipefail
cd "$(dirname "$0")/.."

BASELINE=scripts/lint_baseline.json
CURRENT=$(mktemp)
trap 'rm -f "$CURRENT"' EXIT

# the CLI exits 1 when it finds violations; the diff below reports them
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python -m deeplearning4j_tpu.lint deeplearning4j_tpu --json \
  > "$CURRENT" || true

MODE=gate
[ "${1-}" = "--update" ] && MODE=update

MODE=$MODE CURRENT=$CURRENT BASELINE=$BASELINE python - <<'EOF'
import json
import os
import sys

cur = json.load(open(os.environ["CURRENT"]))
versions = cur.get("rule_versions", {})


def sup_keys(report):
    return {(s["rule"], s["path"], s.get("reason", ""),
             versions.get(s["rule"], ""))
            for s in report.get("suppressed", [])}


if os.environ["MODE"] == "update":
    baseline = {
        "comment": "graftlint baseline — regenerate with "
                   "./scripts/lint_gate.sh --update after reviewing "
                   "suppression changes; rule_version pins the rule "
                   "implementation each suppression was reviewed against",
        "files_scanned": cur["files_scanned"],
        "suppressed": [
            {"rule": r, "path": p, "reason": why, "rule_version": ver}
            for r, p, why, ver in sorted(sup_keys(cur))],
    }
    with open(os.environ["BASELINE"], "w") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"baseline updated: {len(baseline['suppressed'])} suppression(s)")
    sys.exit(0)

failed = False
if cur["violations"] or cur["errors"]:
    failed = True
    for e in cur["errors"]:
        print(f"ERROR {e}")
    for v in cur["violations"]:
        print(f"{v['path']}:{v['line']}: [{v['rule']}] {v['message']}")

base = json.load(open(os.environ["BASELINE"]))
base_keys = {(s["rule"], s["path"], s["reason"],
              s.get("rule_version", ""))
             for s in base["suppressed"]}
cur_keys = sup_keys(cur)
stale = {k[0] for k in base_keys
         if k[3] and versions.get(k[0]) and k[3] != versions[k[0]]}
for rule in sorted(stale):
    failed = True
    print(f"rule '{rule}' implementation changed since the baseline was "
          "reviewed — its suppressions are stale; re-review them and "
          "./scripts/lint_gate.sh --update")
for key in sorted(cur_keys - base_keys):
    if key[0] in stale:
        continue  # already reported as a stale-rule re-review above
    failed = True
    print("new suppression not in baseline: "
          "[%s] %s (%s)" % key[:3])
for key in sorted(base_keys - cur_keys):
    if key[0] in stale:
        continue
    failed = True
    print("baseline suppression no longer present (run --update): "
          "[%s] %s (%s)" % key[:3])

if failed:
    print("lint gate FAILED — fix the findings or, for reviewed "
          "suppression changes, ./scripts/lint_gate.sh --update",
          file=sys.stderr)
    sys.exit(1)
print(f"lint gate ok: {cur['files_scanned']} files, "
      f"{len(cur_keys)} suppression(s) matching baseline")
EOF
