#!/bin/bash
# Capture the full benchmark grid on the real chip in one relay-healthy window.
# Appends one JSON line per run to scripts/bench_log.jsonl (never overwrites).
# Usage: scripts/bench_capture.sh [quick|full]
set -u
cd "$(dirname "$0")/.."
LOG=scripts/bench_log.jsonl
MODE=${1:-full}

# Capture-first (ROADMAP item 1): arm the first-healthy profile trigger so
# the FIRST healthy relay window in this grid carries an XPlane attribution
# capture (bench.py attaches the category split to that row); the marker
# file under DL4J_PROFILE_DIR then stops every later row in the cool-down
# from re-paying the trace overhead.
export DL4J_PROFILE_TRIGGER=${DL4J_PROFILE_TRIGGER:-first-healthy}
export DL4J_PROFILE_DIR=${DL4J_PROFILE_DIR:-scripts/profiles}

# Only one capture grid at a time: the armed watcher may probe-and-capture
# while a manual run is mid-grid; the latecomer exits instead of interleaving
# half-duplicate rows.
exec 9>scripts/.bench_capture.lock
if ! flock -n 9; then
    echo "another bench_capture is running; exiting" >&2
    exit 0
fi

# Arm the relay watcher at minute 0 (VERDICT #2): if THIS capture hits a down
# relay, the watcher is already probing and converts any later healthy window
# into driver-consumable rows. DL4J_FROM_WATCHER guards recursion when the
# watcher itself invokes this script.
WINDOW_TS=$(date -u +%FT%TZ)
if [ "${DL4J_FROM_WATCHER:-0}" != "1" ] \
        && ! pgrep -f "relay_watch.sh" >/dev/null 2>&1; then
    nohup bash scripts/relay_watch.sh >/dev/null 2>&1 &
    echo "armed relay_watch.sh (pid $!) at $WINDOW_TS" >&2
fi

watcher_up() {
    pgrep -f "relay_watch.sh" >/dev/null 2>&1 && echo true || echo false
}

run() {
    echo "--- bench $* $(date -u +%H:%M:%S)" >&2
    out=$(timeout 560 python bench.py "$@" --attempts 1 --attempt-timeout 480 2>/dev/null | tail -1)
    [ -n "$out" ] || out=null   # keep bench_log.jsonl valid per-line JSON
    # each row carries the watcher's up/down state and this capture window's
    # start, so the driver can tell watcher-produced evidence from manual runs
    echo "{\"args\": \"$*\", \"ts\": \"$(date -u +%FT%TZ)\", \"watcher\": {\"up\": $(watcher_up), \"window_start\": \"$WINDOW_TS\"}, \"rec\": $out}" >> "$LOG"
    echo "$out" | head -c 300 >&2; echo >&2
}

# headline configs: bare = per-model measured-best dtype (round-5);
# --bf16-matmul is the A/B twin
run --model resnet50
run --model resnet50 --bf16-matmul
run --model transformer
run --model transformer --bf16-matmul
# the MFU-floor row (VERDICT #7, ISSUE 6) in the ALWAYS-RUN set: one record
# carries the scan/fused/pallas three-way A/B of the recurrent engine at MXU
# width — capture-first, so the first healthy window prices the new path
run --model char_rnn --hidden 1024
# sharding-engine headline rows (ISSUE 8): the flagship fit paths through
# the partition-rule compile seam — zero3's record must show ~1/N
# param_bytes_per_device, dp_tp prices the Megatron column/row splits
run --model fit_resnet50 --sharding zero3
run --model transformer --sharding dp_tp
# serving-engine headline row (ISSUE 9 + 11): micro-batched vs unbatched
# A/B at the auto-calibrated saturation rate, plus the decode section —
# continuous vs static token streaming and int8 vs dense weights at one
# offered sessions/sec (decode_speedup, decode_ttft_p99_improvement,
# int8_prob_drift ride the row); full records (p50/p99, occupancy,
# recompiles == bucket count) also land in scripts/serve_load.jsonl
run --model serve
# sharded multi-replica serving headline row (ISSUE 12): 4 tensor-parallel
# replicas (8 chips = 4 replicas x 2-way dp_tp slices) behind the least-
# queue router vs the single-replica baseline at the same offered rate —
# replica_speedup and replica_recompiles_match_buckets ride the row (the
# >=1.6x two-replica floor is a capture-host property; single-core CI
# can't exhibit it)
run --model serve --serve-sharding dp_tp --serve-replicas 4
# async-PS headline row (ISSUE 10): straggler A/B — one 4x-slow worker of 4,
# async push/pull vs the sync-DP barrier at equal worker count, plus the
# 2-process TCP loss-parity phase (CPU-measured by design, like serve: the
# win is host-side orchestration, not MXU width)
run --model ps_async
# elastic headline row (ISSUE 13): 4 separate-process workers behind the
# membership oracle, SIGKILL one at 50% of the expected push windows —
# worker_loss_dip_pct and recovery_seconds (time back to 90% of the
# pre-kill rate: lease fence -> shard handoff -> replacement resumes at
# the committed broker offset) ride the row; the same record also lands
# in scripts/ps_ab.jsonl beside the ps_async straggler record
run --model elastic
# host-data-plane rows (ISSUE 14): the shm-transport push-window A/B rides
# the ps_async row (tcp_/shm_push_windows_per_sec + shm_push_speedup — the
# >=1.3x shm floor), and the ingest row A/Bs the batched off-GIL native
# frame decode against the per-record GIL-bound python fallback at
# sample-sized records; both records also land in scripts/ps_ab.jsonl
run --model ps_async --ps-transport shm
run --model ingest
# warm-start compile plane row (ISSUE 15): the default serve and elastic
# rows above already headline the WARM numbers (time_to_ready_s from a
# cache-backed pin, recovery_seconds with the respawned worker loading its
# step executable from disk) with the cold A/B riding along; this cold-only
# row pins the cache-off world as its own config so a warm capture can
# never stand in for the cold baseline after an outage
run --model serve --compile-cache off
# paged decode memory plane row (ISSUE 16): the default serve row above
# already headlines the PAGED numbers (paged_sessions_ratio at equal state
# bytes, paged_bitwise_equal, spec_speedup at the tiny draft's measured
# acceptance); this dense-KV no-draft row pins the old decode world as its
# own config so a paged/spec capture can never stand in for the dense
# baseline after an outage
run --model serve --decode-kv dense --decode-spec-draft none
# autoscaling fleet row (ISSUE 18): the open-loop ramp A/B — SLO-driven
# autoscaled fleet vs a static fleet at the same time-weighted average
# replica count under a 10x offered-load swing; the row carries the
# acceptance floor (ramp_slo_violation_seconds_auto < _static), the
# zero-loss scale-in count and the warm-path scale-out latency. Its own
# config: the default (off) row never stands in for the ramp capture
run --model serve --serve-autoscale on
if [ "$MODE" = full ]; then
    run --model lenet
    run --model lenet --bf16-act
    run --model char_rnn
    run --model char_rnn --bf16-matmul
    # engine A/B at MXU width with the scan oracle as the headline (the
    # hidden-1024 headline row above is auto); speedup fields overlap as a
    # cross-check
    run --model char_rnn --hidden 1024 --lstm-impl scan
    run --model vgg16
    run --model vgg16 --bf16-matmul
    run --model moe
    run --model moe --bf16-matmul
    run --model word2vec
    (export DL4J_FLASH_SWEEP=1; run --model attention)
    # long-context proof: T=16384 runs ONLY via the pallas flash path
    # (bench.py skips the XLA twin past its score-bytes budget)
    run --model attention --seq 16384
    run --model fit_resnet50
    run --model fit_lenet
    # full sharding grid: dp baselines the seam's overhead vs the bare fit
    # rows above; the remaining modes complete the per-rule-set comparison
    run --model fit_resnet50 --sharding dp
    run --model fit_resnet50 --sharding dp_tp
    run --model transformer --sharding dp
    run --model transformer --sharding zero3
    # decode-axis captures: the int8-headlined and static-headlined serve
    # configs (config-distinct from the continuous dense headline row)
    run --model serve --serve-quant int8
    run --model serve --serve-batching static
    # batch sweep for the flagship at the winning dtype
    run --model resnet50 --batch 64
    run --model resnet50 --batch 256
fi
echo "done -> $LOG" >&2
