"""tpu-dl4j: a TPU-native deep-learning framework with the capability surface of
Deeplearning4j 0.7.x (reference surveyed in SURVEY.md).

Architecture is idiomatic JAX/XLA — functional layers over parameter pytrees,
jit-compiled train steps, `jax.sharding.Mesh` data/tensor parallelism over ICI —
not a port of the reference's JVM design.

Top-level convenience re-exports mirror the reference's most-used entry points
(reference: deeplearning4j-nn/src/main/java/org/deeplearning4j/nn).
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

__all__ = [
    "NeuralNetConfiguration",
    "MultiLayerConfiguration",
    "MultiLayerNetwork",
    "__version__",
]
