"""Global dtype / platform policy.

The reference selects a tensor backend via Maven profiles (reference pom.xml:123-150,
nd4j-native vs nd4j-cuda). Here the analogous knob is the JAX platform plus a dtype
policy: parameters are kept in ``param_dtype`` (float32 by default for exact updater
semantics) while matmul/conv compute may run in ``compute_dtype`` (bfloat16 on the MXU).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def set_policy(param_dtype=None, compute_dtype=None, output_dtype=None) -> DtypePolicy:
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=param_dtype or _POLICY.param_dtype,
        compute_dtype=compute_dtype or _POLICY.compute_dtype,
        output_dtype=output_dtype or _POLICY.output_dtype,
    )
    return _POLICY


def at_least_f32(dtype) -> jnp.dtype:
    """The dtype to run precision-critical reductions (norm statistics, loss
    entry points) in: float32 when activations flow as bf16/f16, otherwise the
    incoming dtype unchanged (the float64 gradient-check path must not be
    downcast)."""
    return dtype if jnp.finfo(dtype).bits >= 32 else jnp.dtype(jnp.float32)


def bf16_matmul_policy() -> DtypePolicy:
    """bfloat16 compute on the MXU, float32 params/outputs."""
    return set_policy(compute_dtype=jnp.bfloat16)


def full_bf16_policy() -> DtypePolicy:
    """bfloat16 compute AND activations; float32 params, optimizer state and
    norm statistics.

    Halves activation HBM traffic vs :func:`bf16_matmul_policy` (each layer
    otherwise materializes its output back to float32). Precision-critical
    reductions stay float32 regardless of this policy: batch-norm/layer-norm
    statistics and every registered loss upcast internally (custom callable
    losses are wrapped the same way by ``ops.losses.get_loss``), and gradients
    follow the float32 param dtype, so updater semantics are unchanged.
    VariationalAutoencoder's encoder/decoder matmuls use raw float32 params
    and stay float32 under any policy; AutoEncoder/RBM route through the
    shared dense kernel and follow the policy like every other layer.
    """
    return set_policy(compute_dtype=jnp.bfloat16, output_dtype=jnp.bfloat16)
