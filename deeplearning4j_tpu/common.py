"""Global dtype / platform policy.

The reference selects a tensor backend via Maven profiles (reference pom.xml:123-150,
nd4j-native vs nd4j-cuda). Here the analogous knob is the JAX platform plus a dtype
policy: parameters are kept in ``param_dtype`` (float32 by default for exact updater
semantics) while matmul/conv compute may run in ``compute_dtype`` (bfloat16 on the MXU).

Reduction precision is a first-class policy axis (round-5 lesson: 23% of the
bf16 ResNet-50 step sat in f32 statistics/grad reduce fusions the policy never
asked for):

* ``reduction_dtype`` — accumulator/operand dtype of normalization-statistics
  reductions (batch-norm mean/var, dgamma/dbeta). ``None`` means "at least
  f32" (the safe classic recipe); an explicit ``bfloat16`` keeps the stat
  passes convert-free on bf16 activations.
* ``grad_accum_dtype`` — ``preferred_element_type`` for the dense/conv
  contractions. JAX's transpose rules propagate it into the weight-gradient
  contractions, so an explicit ``float32`` here pins f32 accumulation of
  dW/dx even when both operands are bf16 (Micikevicius et al. mixed-precision
  accumulate-wide discipline) without any post-hoc upcast-reduce. ``None``
  leaves XLA's operand-dtype inference in charge (the pre-round-6 behavior).
"""
from __future__ import annotations

import contextlib
import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32
    # None = derived defaults (see module docstring); both knobs are read at
    # trace time like every other field, so policy_key() must include them
    reduction_dtype: jnp.dtype | None = None
    grad_accum_dtype: jnp.dtype | None = None

    def stat_dtype(self, x_dtype) -> jnp.dtype:
        """Dtype for normalization-statistics reductions on an ``x_dtype``
        tensor: the explicit ``reduction_dtype`` if set, else at-least-f32
        (which also keeps the float64 gradient-check path undowncast)."""
        if self.reduction_dtype is not None:
            # never downcast the f64 gradcheck path: a bf16 reduction policy
            # applies to bf16/f32 activations, not to x64 verification runs
            if jnp.finfo(x_dtype).bits > jnp.finfo(self.reduction_dtype).bits \
                    and jnp.finfo(x_dtype).bits > 32:
                return jnp.dtype(x_dtype)
            return jnp.dtype(self.reduction_dtype)
        return at_least_f32(x_dtype)


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def _dtype_name(d) -> str | None:
    return None if d is None else jnp.dtype(d).name


def policy_key() -> tuple:
    """Hashable identity of the active policy. Networks key their compiled-
    program caches on this: the policy is read at trace time, so a cached
    program silently pins whatever policy was active at first call unless the
    cache key includes it."""
    return (jnp.dtype(_POLICY.param_dtype).name,
            jnp.dtype(_POLICY.compute_dtype).name,
            jnp.dtype(_POLICY.output_dtype).name,
            _dtype_name(_POLICY.reduction_dtype),
            _dtype_name(_POLICY.grad_accum_dtype))


def effective_policy_key(conf_dtype: str | None) -> tuple:
    """The cache key under which a traced program's dtypes are decided.

    A config-declared dtype (GlobalConf.dtype, applied via wrap_with_policy)
    pins the program regardless of the ambient global policy — so such
    programs must NOT be invalidated or re-keyed when the global policy
    changes. Every compiled-program cache in the framework keys on this one
    helper so the rule can't diverge between sites."""
    return (conf_dtype,) if conf_dtype else (None,) + policy_key()


_UNSET = object()


def set_policy(param_dtype=None, compute_dtype=None, output_dtype=None,
               reduction_dtype=_UNSET, grad_accum_dtype=_UNSET) -> DtypePolicy:
    """Update the global policy. The three storage/compute dtypes keep their
    current value when None (they are never legitimately None); the two
    reduction knobs use an explicit unset sentinel because None IS a
    meaningful value for them ("derive the default")."""
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=param_dtype or _POLICY.param_dtype,
        compute_dtype=compute_dtype or _POLICY.compute_dtype,
        output_dtype=output_dtype or _POLICY.output_dtype,
        reduction_dtype=(_POLICY.reduction_dtype if reduction_dtype is _UNSET
                         else reduction_dtype),
        grad_accum_dtype=(_POLICY.grad_accum_dtype if grad_accum_dtype is _UNSET
                          else grad_accum_dtype),
    )
    return _POLICY


def accum_dtype(operand_dtype) -> jnp.dtype | None:
    """``preferred_element_type`` for policy-routed contractions (dense/conv
    forward ops — JAX transpose rules carry it into the weight-grad
    contractions). Returns the policy's ``grad_accum_dtype`` only when it
    WIDENS the operands; already-wide operands (plain f32 runs, the f64
    gradient-check path) return None and lower exactly as before."""
    g = _POLICY.grad_accum_dtype
    if g is None:
        return None
    if jnp.finfo(operand_dtype).bits >= jnp.finfo(g).bits:
        return None
    return jnp.dtype(g)


def at_least_f32(dtype) -> jnp.dtype:
    """The dtype to run precision-critical reductions (norm statistics, loss
    entry points) in: float32 when activations flow as bf16/f16, otherwise the
    incoming dtype unchanged (the float64 gradient-check path must not be
    downcast)."""
    return dtype if jnp.finfo(dtype).bits >= 32 else jnp.dtype(jnp.float32)


def bf16_matmul_policy() -> DtypePolicy:
    """bfloat16 compute on the MXU, float32 params/outputs."""
    return set_policy(compute_dtype=jnp.bfloat16)


_NAMED_POLICIES = {
    "float32": DtypePolicy(),
    "bfloat16": DtypePolicy(compute_dtype=jnp.bfloat16),
    "bfloat16_full": DtypePolicy(compute_dtype=jnp.bfloat16,
                                 output_dtype=jnp.bfloat16),
    # the flagship training recipe: bf16 storage/IO AND bf16 single-pass
    # statistics (no standalone f32 upcast-reduce fusions), with weight-grad
    # contractions pinned to f32 accumulation so updater numerics hold
    "bfloat16_flagship": DtypePolicy(compute_dtype=jnp.bfloat16,
                                     output_dtype=jnp.bfloat16,
                                     reduction_dtype=jnp.bfloat16,
                                     grad_accum_dtype=jnp.float32),
}


def resolve_policy(name: str) -> DtypePolicy:
    """Named policy for the config DSL's ``dtype`` field."""
    key = str(name).lower()
    if key not in _NAMED_POLICIES:
        raise ValueError(f"Unknown dtype policy '{name}'. "
                         f"Known: {sorted(_NAMED_POLICIES)}")
    return _NAMED_POLICIES[key]


@contextlib.contextmanager
def override_policy(name: str):
    """Temporarily install a named policy. Wrapped around function BODIES that
    jit traces (wrap_with_policy): tracing runs the body under the override,
    baking the dtype into the compiled program; execution never re-enters the
    Python body, so the global policy is untouched at run time."""
    global _POLICY
    saved = _POLICY
    _POLICY = resolve_policy(name)
    try:
        yield
    finally:
        _POLICY = saved


def wrap_with_policy(fn, name: str | None):
    """Make ``fn`` trace under the named policy (no-op when name is None)."""
    if not name:
        return fn

    def wrapped(*args, **kwargs):
        with override_policy(name):
            return fn(*args, **kwargs)
    return wrapped


def full_bf16_policy() -> DtypePolicy:
    """bfloat16 compute AND activations; float32 params, optimizer state and
    norm statistics.

    Halves activation HBM traffic vs :func:`bf16_matmul_policy` (each layer
    otherwise materializes its output back to float32). Precision-critical
    reductions stay float32 regardless of this policy: batch-norm/layer-norm
    statistics and every registered loss upcast internally (custom callable
    losses are wrapped the same way by ``ops.losses.get_loss``), and gradients
    follow the float32 param dtype, so updater semantics are unchanged.
    VariationalAutoencoder's encoder/decoder matmuls use raw float32 params
    and stay float32 under any policy; AutoEncoder/RBM route through the
    shared dense kernel and follow the policy like every other layer.
    For bf16 statistics too (the measured flagship recipe), use
    :func:`flagship_bf16_policy` / the ``"bfloat16_flagship"`` named policy.
    """
    return set_policy(compute_dtype=jnp.bfloat16, output_dtype=jnp.bfloat16,
                      reduction_dtype=None, grad_accum_dtype=None)


def flagship_bf16_policy() -> DtypePolicy:
    """The measured flagship training recipe (``"bfloat16_flagship"``):
    everything :func:`full_bf16_policy` does, PLUS bf16 single-pass
    normalization statistics (kills the standalone f32 upcast-reduce fusions
    — 23% of ResNet-50 bf16 device time in the r5 profile) and f32-pinned
    weight-gradient accumulation via ``preferred_element_type``."""
    return set_policy(compute_dtype=jnp.bfloat16, output_dtype=jnp.bfloat16,
                      reduction_dtype=jnp.bfloat16,
                      grad_accum_dtype=jnp.float32)
