"""Global dtype / platform policy.

The reference selects a tensor backend via Maven profiles (reference pom.xml:123-150,
nd4j-native vs nd4j-cuda). Here the analogous knob is the JAX platform plus a dtype
policy: parameters are kept in ``param_dtype`` (float32 by default for exact updater
semantics) while matmul/conv compute may run in ``compute_dtype`` (bfloat16 on the MXU).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def set_policy(param_dtype=None, compute_dtype=None, output_dtype=None) -> DtypePolicy:
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=param_dtype or _POLICY.param_dtype,
        compute_dtype=compute_dtype or _POLICY.compute_dtype,
        output_dtype=output_dtype or _POLICY.output_dtype,
    )
    return _POLICY


def bf16_matmul_policy() -> DtypePolicy:
    """bfloat16 compute on the MXU, float32 params/outputs."""
    return set_policy(compute_dtype=jnp.bfloat16)
