"""Global dtype / platform policy.

The reference selects a tensor backend via Maven profiles (reference pom.xml:123-150,
nd4j-native vs nd4j-cuda). Here the analogous knob is the JAX platform plus a dtype
policy: parameters are kept in ``param_dtype`` (float32 by default for exact updater
semantics) while matmul/conv compute may run in ``compute_dtype`` (bfloat16 on the MXU).
"""
from __future__ import annotations

import contextlib
import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def policy_key() -> tuple:
    """Hashable identity of the active policy. Networks key their compiled-
    program caches on this: the policy is read at trace time, so a cached
    program silently pins whatever policy was active at first call unless the
    cache key includes it."""
    return (jnp.dtype(_POLICY.param_dtype).name,
            jnp.dtype(_POLICY.compute_dtype).name,
            jnp.dtype(_POLICY.output_dtype).name)


def effective_policy_key(conf_dtype: str | None) -> tuple:
    """The cache key under which a traced program's dtypes are decided.

    A config-declared dtype (GlobalConf.dtype, applied via wrap_with_policy)
    pins the program regardless of the ambient global policy — so such
    programs must NOT be invalidated or re-keyed when the global policy
    changes. Every compiled-program cache in the framework keys on this one
    helper so the rule can't diverge between sites."""
    return (conf_dtype,) if conf_dtype else (None,) + policy_key()


def set_policy(param_dtype=None, compute_dtype=None, output_dtype=None) -> DtypePolicy:
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=param_dtype or _POLICY.param_dtype,
        compute_dtype=compute_dtype or _POLICY.compute_dtype,
        output_dtype=output_dtype or _POLICY.output_dtype,
    )
    return _POLICY


def at_least_f32(dtype) -> jnp.dtype:
    """The dtype to run precision-critical reductions (norm statistics, loss
    entry points) in: float32 when activations flow as bf16/f16, otherwise the
    incoming dtype unchanged (the float64 gradient-check path must not be
    downcast)."""
    return dtype if jnp.finfo(dtype).bits >= 32 else jnp.dtype(jnp.float32)


def bf16_matmul_policy() -> DtypePolicy:
    """bfloat16 compute on the MXU, float32 params/outputs."""
    return set_policy(compute_dtype=jnp.bfloat16)


_NAMED_POLICIES = {
    "float32": DtypePolicy(),
    "bfloat16": DtypePolicy(compute_dtype=jnp.bfloat16),
    "bfloat16_full": DtypePolicy(compute_dtype=jnp.bfloat16,
                                 output_dtype=jnp.bfloat16),
}


def resolve_policy(name: str) -> DtypePolicy:
    """Named policy for the config DSL's ``dtype`` field."""
    key = str(name).lower()
    if key not in _NAMED_POLICIES:
        raise ValueError(f"Unknown dtype policy '{name}'. "
                         f"Known: {sorted(_NAMED_POLICIES)}")
    return _NAMED_POLICIES[key]


@contextlib.contextmanager
def override_policy(name: str):
    """Temporarily install a named policy. Wrapped around function BODIES that
    jit traces (wrap_with_policy): tracing runs the body under the override,
    baking the dtype into the compiled program; execution never re-enters the
    Python body, so the global policy is untouched at run time."""
    global _POLICY
    saved = _POLICY
    _POLICY = resolve_policy(name)
    try:
        yield
    finally:
        _POLICY = saved


def wrap_with_policy(fn, name: str | None):
    """Make ``fn`` trace under the named policy (no-op when name is None)."""
    if not name:
        return fn

    def wrapped(*args, **kwargs):
        with override_policy(name):
            return fn(*args, **kwargs)
    return wrapped


def full_bf16_policy() -> DtypePolicy:
    """bfloat16 compute AND activations; float32 params, optimizer state and
    norm statistics.

    Halves activation HBM traffic vs :func:`bf16_matmul_policy` (each layer
    otherwise materializes its output back to float32). Precision-critical
    reductions stay float32 regardless of this policy: batch-norm/layer-norm
    statistics and every registered loss upcast internally (custom callable
    losses are wrapped the same way by ``ops.losses.get_loss``), and gradients
    follow the float32 param dtype, so updater semantics are unchanged.
    VariationalAutoencoder's encoder/decoder matmuls use raw float32 params
    and stay float32 under any policy; AutoEncoder/RBM route through the
    shared dense kernel and follow the policy like every other layer.
    """
    return set_policy(compute_dtype=jnp.bfloat16, output_dtype=jnp.bfloat16)
