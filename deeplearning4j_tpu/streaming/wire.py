"""Length-prefixed framed messages over stdlib sockets + ndarray serde.

One wire format shared by the two loopback transports in this repo — the
async parameter-server TCP backend (parallel/ps_transport.py) and the
streaming broker (streaming/broker.py). A frame is::

    !II          header_len, payload_len   (8-byte big-endian prefix)
    header_len   UTF-8 JSON header (op, offsets, array metadata, ...)
    payload_len  raw array bytes (concatenated, C-order)

Arrays ride the payload with their (name, dtype, shape, codec) recorded in
the header under "arrays", so a frame is self-describing. The optional
``bf16`` codec halves float32 wire bytes (round-to-nearest via ml_dtypes,
which JAX already depends on) — used for pushed parameter deltas where a
half-precision delta is within SGD noise; canonical server state stays f32.

Zero-copy discipline (the host data plane, ISSUE 14): tensor bytes are
handled as ``memoryview``s end to end. ``encode_array`` returns a view of
the array's own buffer (the bf16 codec converts — that is arithmetic, not a
copy bug — and returns a view of the converted array); ``pack_arrays``
returns the views unjoined; ``send_frame`` scatter-gathers them through
``socket.sendmsg``; ``recv_frame`` reads with ``recv_into`` — into a
caller-provided reusable buffer when the call site can prove single-frame
lifetime, else into one fresh ``bytearray`` whose views the decoded arrays
keep alive. ``decode_array`` returns a read-only ``np.frombuffer`` view by
default. Every byte that IS copied on this path (``copy=True`` decodes, the
``sendmsg``-unavailable fallback) is counted in ``dl4j_wire_copy_bytes_total``
— the counter staying flat under load is the proof the copies are gone.

Trace propagation: the header key ``traceparent`` (and the same key inside
a broker message's ``meta``) is RESERVED for a W3C traceparent string. Both
transports stamp it on outbound frames when an ambient span exists and
parent their server-side handling spans from it — that is the entire
cross-process trace-stitching contract; the framing itself is unchanged.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import WIRE_COPY_BYTES_TOTAL

_PREFIX = struct.Struct("!II")

#: codecs understood by encode_array/decode_array
CODECS = ("none", "bf16")

#: one buffer or a scatter-gather list of them (send_frame's payload type)
Buffers = Union[bytes, bytearray, memoryview, Sequence[Union[bytes, bytearray, memoryview]]]

_copy_bytes = _obs_registry().counter(
    WIRE_COPY_BYTES_TOTAL,
    "tensor bytes COPIED on the wire hot path, by site — flat under load "
    "is the zero-copy proof; any growth names the regressing call site")
_copy_decode = _copy_bytes.labels(site="decode")
_copy_send = _copy_bytes.labels(site="send_fallback")


def _bf16_dtype():
    import ml_dtypes  # bundled with jax; no new dependency
    return ml_dtypes.bfloat16


def _byteview(buf) -> memoryview:
    """A flat unsigned-byte view of any buffer (ndarray, bytes, bytearray,
    memoryview) without copying."""
    v = buf if isinstance(buf, memoryview) else memoryview(buf)
    return v if v.format == "B" and v.ndim == 1 else v.cast("B")


def encode_array(a: np.ndarray, codec: str = "none",
                 ) -> Tuple[dict, memoryview]:
    """-> (metadata dict, payload view). The view aliases the (contiguous)
    array's own buffer — the caller must not mutate ``a`` until the view has
    been sent. ``bf16`` only compresses floating arrays; integer arrays pass
    through unchanged (and say so in the meta)."""
    shape = list(a.shape)  # before ascontiguousarray, which 1-d-ifies 0-dim
    a = np.ascontiguousarray(a)
    if codec == "bf16" and a.dtype.kind == "f":
        meta = {"dtype": str(a.dtype), "shape": shape, "codec": "bf16"}
        # codec conversion, not a copy bug; the uint16 view is free (bf16
        # ndarrays don't export the buffer protocol themselves)
        a = np.asarray(a, dtype=_bf16_dtype()).view(np.uint16)
    elif codec in CODECS:
        meta = {"dtype": str(a.dtype), "shape": shape, "codec": "none"}
    else:
        raise ValueError(f"unknown wire codec {codec!r}; expected {CODECS}")
    return meta, _byteview(a.reshape(-1))  # flatten is a view (contiguous)


def decode_array(meta: dict, buf, *, copy: bool = False) -> np.ndarray:
    """Decode one array from its payload bytes/view.

    Default is zero-copy: a read-only ``np.frombuffer`` view over ``buf``
    (the bf16 codec widens to the recorded dtype — conversion, not a copy).
    ``copy=True`` materializes a private writable array and bills the bytes
    to ``dl4j_wire_copy_bytes_total{site="decode"}``.
    """
    shape = tuple(meta["shape"])
    if meta["codec"] == "bf16":
        a = np.frombuffer(buf, dtype=_bf16_dtype()).astype(meta["dtype"])
    else:
        a = np.frombuffer(buf, dtype=np.dtype(meta["dtype"]))  # lint: hot-path-copy-ok (view, no .copy(): the zero-copy decode itself)
        if copy:
            _copy_decode.inc(a.nbytes)
            a = a.copy()
    return a.reshape(shape)


def pack_arrays(arrays: Dict[str, np.ndarray], codec: str = "none",
                ) -> Tuple[List[dict], List[memoryview]]:
    """Named arrays -> ordered metadata list + scatter-gather view list
    (feed the list straight to ``send_frame``; nothing is joined)."""
    metas, views = [], []
    for name, a in arrays.items():
        meta, buf = encode_array(np.asarray(a), codec)
        meta["name"] = name
        meta["nbytes"] = buf.nbytes
        metas.append(meta)
        views.append(buf)
    return metas, views


def unpack_arrays(metas: List[dict], payload) -> Dict[str, np.ndarray]:
    """Inverse of pack_arrays; ``payload`` is the received frame payload
    (bytes or view). Arrays are zero-copy views into it."""
    view = _byteview(payload) if payload else memoryview(b"")
    out, off = {}, 0
    for meta in metas:
        n = meta["nbytes"]
        out[meta["name"]] = decode_array(meta, view[off:off + n])
        off += n
    return out


def send_frame(sock: socket.socket, header: dict,
               payload: Buffers = b"") -> int:
    """Write one frame; returns bytes put on the wire. ``payload`` may be a
    single buffer or a list of buffers — the scatter-gather path hands the
    views to ``socket.sendmsg`` untouched (no join, no copy)."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    bufs = payload if isinstance(payload, (list, tuple)) else [payload]
    views = [_byteview(b) for b in bufs if len(b)]
    payload_len = sum(v.nbytes for v in views)
    prefix = _PREFIX.pack(len(hdr), payload_len)
    total = len(prefix) + len(hdr) + payload_len
    pending = [memoryview(prefix), memoryview(hdr)] + views
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:  # non-POSIX fallback: one joined copy, billed
        _copy_send.inc(payload_len)
        sock.sendall(b"".join(pending))
        return total
    while pending:
        n = sendmsg(pending)
        while pending and n >= pending[0].nbytes:
            n -= pending[0].nbytes
            pending.pop(0)
        if pending and n:
            pending[0] = pending[0][n:]
    return total


def _recv_into_exact(sock: socket.socket, view: memoryview) -> None:
    while view.nbytes:
        n = sock.recv_into(view, view.nbytes)
        if not n:
            raise ConnectionError("peer closed mid-frame")
        view = view[n:]


def recv_frame(sock: socket.socket, buffer: Optional[bytearray] = None,
               ) -> Tuple[dict, memoryview]:
    """Read one frame; raises ConnectionError on EOF / truncated stream.

    Returns (header, payload view). Without ``buffer`` the payload lands in
    one fresh bytearray per frame — safe to keep (decoded arrays hold the
    view). With a reusable ``buffer`` (grown in place as needed) the NEXT
    recv_frame on the same buffer overwrites it: only for call sites that
    fully consume the payload before receiving again, e.g. the PS frontend
    applying a delta under the server lock.
    """
    prefix = bytearray(_PREFIX.size)
    _recv_into_exact(sock, memoryview(prefix))
    hdr_len, payload_len = _PREFIX.unpack(prefix)
    hdr = bytearray(hdr_len)
    _recv_into_exact(sock, memoryview(hdr))
    header = json.loads(hdr.decode("utf-8"))
    if not payload_len:
        return header, memoryview(b"")
    if buffer is None:
        buffer = bytearray(payload_len)
    elif len(buffer) < payload_len:
        try:
            buffer.extend(bytes(payload_len - len(buffer)))
        except BufferError:
            # a prior frame's view is still alive: fresh allocation instead
            # of corrupting it (reuse resumes once the caller drops the view)
            buffer = bytearray(payload_len)
    view = memoryview(buffer)[:payload_len]
    _recv_into_exact(sock, view)
    return header, view.toreadonly()


def request(sock: socket.socket, header: dict, payload: Buffers = b"",
            buffer: Optional[bytearray] = None,
            ) -> Tuple[dict, memoryview, int]:
    """One RPC round-trip: send a frame, read the reply frame.
    Returns (reply_header, reply_payload, bytes_sent)."""
    sent = send_frame(sock, header, payload)
    reply, buf = recv_frame(sock, buffer)
    if "error" in reply:
        raise RuntimeError(f"peer error for op={header.get('op')!r}: "
                           f"{reply['error']}")
    return reply, buf, sent


def connect(addr: Tuple[str, int], timeout: Optional[float] = 30.0,
            ) -> socket.socket:
    sock = socket.create_connection(addr, timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
