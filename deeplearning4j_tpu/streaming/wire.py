"""Length-prefixed framed messages over stdlib sockets + ndarray serde.

One wire format shared by the two loopback transports in this repo — the
async parameter-server TCP backend (parallel/ps_transport.py) and the
streaming broker (streaming/broker.py). A frame is::

    !II          header_len, payload_len   (8-byte big-endian prefix)
    header_len   UTF-8 JSON header (op, offsets, array metadata, ...)
    payload_len  raw array bytes (concatenated, C-order)

Arrays ride the payload with their (name, dtype, shape, codec) recorded in
the header under "arrays", so a frame is self-describing. The optional
``bf16`` codec halves float32 wire bytes (round-to-nearest via ml_dtypes,
which JAX already depends on) — used for pushed parameter deltas where a
half-precision delta is within SGD noise; canonical server state stays f32.
"""
from __future__ import annotations

import json
import socket
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

_PREFIX = struct.Struct("!II")

#: codecs understood by encode_array/decode_array
CODECS = ("none", "bf16")


def _bf16_dtype():
    import ml_dtypes  # bundled with jax; no new dependency
    return ml_dtypes.bfloat16


def encode_array(a: np.ndarray, codec: str = "none") -> Tuple[dict, bytes]:
    """-> (metadata dict, payload bytes). ``bf16`` only compresses floating
    arrays; integer arrays pass through unchanged (and say so in the meta)."""
    a = np.ascontiguousarray(a)
    if codec == "bf16" and a.dtype.kind == "f":
        buf = np.asarray(a, dtype=_bf16_dtype()).tobytes()
        meta = {"dtype": str(a.dtype), "shape": list(a.shape),
                "codec": "bf16"}
    elif codec in CODECS:
        buf = a.tobytes()
        meta = {"dtype": str(a.dtype), "shape": list(a.shape),
                "codec": "none"}
    else:
        raise ValueError(f"unknown wire codec {codec!r}; expected {CODECS}")
    return meta, buf


def decode_array(meta: dict, buf: bytes) -> np.ndarray:
    shape = tuple(meta["shape"])
    if meta["codec"] == "bf16":
        a = np.frombuffer(buf, dtype=_bf16_dtype()).astype(meta["dtype"])
    else:
        a = np.frombuffer(buf, dtype=np.dtype(meta["dtype"])).copy()
    return a.reshape(shape)


def pack_arrays(arrays: Dict[str, np.ndarray],
                codec: str = "none") -> Tuple[List[dict], bytes]:
    """Concatenate named arrays into one payload + ordered metadata list."""
    metas, chunks = [], []
    for name, a in arrays.items():
        meta, buf = encode_array(np.asarray(a), codec)
        meta["name"] = name
        meta["nbytes"] = len(buf)
        metas.append(meta)
        chunks.append(buf)
    return metas, b"".join(chunks)


def unpack_arrays(metas: List[dict], payload: bytes) -> Dict[str, np.ndarray]:
    out, off = {}, 0
    for meta in metas:
        n = meta["nbytes"]
        out[meta["name"]] = decode_array(meta, payload[off:off + n])
        off += n
    return out


def send_frame(sock: socket.socket, header: dict,
               payload: bytes = b"") -> int:
    """Write one frame; returns bytes put on the wire."""
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    buf = _PREFIX.pack(len(hdr), len(payload)) + hdr + payload
    sock.sendall(buf)
    return len(buf)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    """Read one frame; raises ConnectionError on EOF / truncated stream."""
    hdr_len, payload_len = _PREFIX.unpack(_recv_exact(sock, _PREFIX.size))
    header = json.loads(_recv_exact(sock, hdr_len).decode("utf-8"))
    payload = _recv_exact(sock, payload_len) if payload_len else b""
    return header, payload


def request(sock: socket.socket, header: dict,
            payload: bytes = b"") -> Tuple[dict, bytes, int]:
    """One RPC round-trip: send a frame, read the reply frame.
    Returns (reply_header, reply_payload, bytes_sent)."""
    sent = send_frame(sock, header, payload)
    reply, buf = recv_frame(sock)
    if "error" in reply:
        raise RuntimeError(f"peer error for op={header.get('op')!r}: "
                           f"{reply['error']}")
    return reply, buf, sent


def connect(addr: Tuple[str, int], timeout: Optional[float] = 30.0,
            ) -> socket.socket:
    sock = socket.create_connection(addr, timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
