"""Loopback TCP broker + reconnecting consumer behind the Route API.

Reference: dl4j-streaming's Camel+Kafka routes (CamelKafkaRouteBuilder) —
the broker role Kafka played maps onto a stdlib-socket loopback server with
Kafka's two load-bearing properties kept:

* **offset-addressed topic logs** — every published message gets a dense
  offset in its topic; consumers fetch *from* an offset, so delivery is
  replayable;
* **committed consumer offsets** — a consumer group commits the offset it
  has fully handled; after a connection drop the consumer reconnects, asks
  the broker for its committed offset, and resumes from the next message —
  zero message loss (at-least-once: the one in-flight message may redeliver
  if the drop lands between handling and commit).

Wire format is streaming/wire.py's framed JSON+payload (the same frames the
parameter-server TCP transport speaks). ``ReconnectingConsumer`` implements
the queue seam ``Route`` consumes (`get`/`task_done`/`unfinished_tasks`/
`all_tasks_done`), so ``Route(consumer, handler)`` — and therefore
``BrokerTrainingRoute`` — works unchanged over the network.
"""
from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import (
    BROKER_MESSAGES_TOTAL, BROKER_RECONNECTS_TOTAL,
)
from deeplearning4j_tpu.observability.tracing import (
    TRACEPARENT_HEADER,
    current_span as _current_span,
    parse_traceparent as _parse_traceparent,
    start_span as _start_span,
)
from deeplearning4j_tpu.streaming import Route, wire

_messages = _obs_registry().counter(
    BROKER_MESSAGES_TOTAL, "broker messages by op (publish|deliver)")
_published = _messages.labels(op="publish")
_delivered = _messages.labels(op="deliver")
_reconnects = _obs_registry().counter(
    BROKER_RECONNECTS_TOTAL, "consumer reconnects after a dropped broker "
                             "connection").labels()


class LoopbackBroker:
    """In-memory topic logs served over loopback TCP (the Kafka stand-in).

    Ops: publish(topic)->offset; fetch(topic, offset, max_wait_s) -> one
    message or {"eof": true}; commit(topic, group, offset); committed(topic,
    group) -> offset. `drop_connections()` force-closes every live client
    socket — the fault injection the reconnect tests lean on.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host, self._port = host, port
        self._topics: Dict[str, List[Tuple[dict, bytes]]] = {}
        self._commits: Dict[Tuple[str, str], int] = {}
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._lsock: Optional[socket.socket] = None
        self._conns: List[socket.socket] = []
        self._threads: List[threading.Thread] = []

    @property
    def port(self) -> int:
        return self._port

    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def start(self) -> "LoopbackBroker":
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((self._host, self._port))
        self._lsock.listen(32)
        self._lsock.settimeout(0.2)
        self._port = self._lsock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="broker-accept")
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed during stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._cond:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="broker-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    header, payload = wire.recv_frame(conn)
                    reply, buf = self._handle(header, payload)
                    wire.send_frame(conn, reply, buf)
                except (ConnectionError, OSError):
                    return  # client gone (or dropped by fault injection)
                except Exception as e:
                    _flight_recorder().record("broker_error", error=repr(e))
                    try:
                        wire.send_frame(conn, {"error": repr(e)})
                    except OSError:  # lint: swallowed-exception-ok (peer already gone; error recorded above)
                        pass
                    return

    def _handle(self, header: dict, payload: bytes):
        op = header.get("op")
        if op == "publish":
            with self._cond:
                log = self._topics.setdefault(header["topic"], [])
                offset = len(log)
                log.append((header.get("meta", {}), payload))
                self._cond.notify_all()
            _published.inc()
            return {"offset": offset}, b""
        if op == "fetch":
            topic, offset = header["topic"], int(header["offset"])
            deadline = time.time() + float(header.get("max_wait_s", 0.0))
            with self._cond:
                while True:
                    log = self._topics.get(topic, [])
                    if offset < len(log):
                        meta, buf = log[offset]
                        _delivered.inc()
                        return {"offset": offset, "meta": meta}, buf
                    left = deadline - time.time()
                    if left <= 0 or self._stop.is_set():
                        return {"eof": True}, b""
                    self._cond.wait(min(left, 0.1))
        if op == "commit":
            with self._cond:
                key = (header["topic"], header["group"])
                self._commits[key] = max(self._commits.get(key, -1),
                                         int(header["offset"]))
            return {"ok": True}, b""
        if op == "committed":
            with self._cond:
                off = self._commits.get((header["topic"], header["group"]),
                                        -1)
            return {"offset": off}, b""
        raise ValueError(f"unknown broker op {op!r}")

    def depth(self, topic: str) -> int:
        with self._cond:
            return len(self._topics.get(topic, []))

    def committed(self, topic: str, group: str) -> int:
        """In-process view of a group's committed offset (-1 = nothing
        committed) — what the elastic coordinator compares to a shard
        topic's fin offset to decide whether a dead worker left uncommitted
        samples behind."""
        with self._cond:
            return self._commits.get((topic, group), -1)

    def drop_connections(self) -> int:
        """Fault injection: force-close every live client socket (consumers
        must reconnect and resume from their committed offset)."""
        with self._cond:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:  # lint: swallowed-exception-ok (racing a client that closed first is the point)
                pass
            conn.close()
        _flight_recorder().record("broker_drop_connections", n=len(conns))
        return len(conns)

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._lsock is not None:
            self._lsock.close()
        self.drop_connections()
        for t in self._threads:
            t.join(timeout=5)


class BrokerProducer:
    """Publish framed array messages to a topic. A dead connection (e.g.
    after the broker's fault-injection drop) reconnects and retries once —
    a publish either returns its offset or raises."""

    def __init__(self, addr: Tuple[str, int]):
        self._addr = tuple(addr)
        self._sock = wire.connect(self._addr)

    def publish(self, topic: str, arrays: Dict[str, np.ndarray],
                meta: Optional[dict] = None, codec: str = "none") -> int:
        metas, payload = wire.pack_arrays(arrays, codec)
        full_meta = dict(meta or {}, arrays=metas)
        # wire-propagated tracing: an ambient span (the coordinator's
        # publish window, a route's ingest span) rides the message meta so
        # the consumer's process can parent under the same trace id
        sp = _current_span()
        if sp is not None and TRACEPARENT_HEADER not in full_meta:
            full_meta[TRACEPARENT_HEADER] = sp.traceparent()
        header = {"op": "publish", "topic": topic, "meta": full_meta}
        try:
            reply, _, _ = wire.request(self._sock, header, payload)
        except (ConnectionError, OSError):
            self._sock.close()
            self._sock = wire.connect(self._addr)
            reply, _, _ = wire.request(self._sock, header, payload)
        return reply["offset"]

    def close(self) -> None:
        self._sock.close()


class ReconnectingConsumer:
    """A queue-shaped view of one (topic, group) subscription.

    Implements the exact seam ``Route._run``/``drain`` consume — ``get``,
    ``task_done``, ``unfinished_tasks``, ``all_tasks_done`` — over a broker
    connection that is allowed to die: every socket error triggers a
    reconnect + resume from the server-side committed offset, so a forced
    drop mid-stream loses nothing. ``task_done`` commits the delivered
    offset (handled-then-commit => at-least-once).
    """

    def __init__(self, addr: Tuple[str, int], topic: str,
                 group: str = "default", reconnect_backoff_s: float = 0.05,
                 reconnect_backoff_cap_s: float = 1.0,
                 native_decode: bool = False):
        self._addr = tuple(addr)
        self.topic, self.group = topic, group
        self._backoff = reconnect_backoff_s
        self._backoff_cap = max(reconnect_backoff_s, reconnect_backoff_cap_s)
        self._cur_backoff = reconnect_backoff_s
        self._native_decode = native_decode
        self._sock: Optional[socket.socket] = None
        self._next: Optional[int] = None   # next offset to fetch
        self._delivered: Optional[int] = None  # offset awaiting task_done
        self._last_delivered: Optional[int] = None  # high-water, never reset
        self.reconnects = 0
        self.unfinished_tasks = 0
        self.all_tasks_done = threading.Condition()
        #: SpanRef of the last delivered message's consume span (None when
        #: the message carried no traceparent) — the worker run loop binds
        #: this onto its PS transport so the push window stitches into the
        #: producer's trace
        self.last_trace_ref = None

    # ------------------------------------------------------------ transport
    def _connect(self) -> None:
        self._sock = wire.connect(self._addr, timeout=10.0)
        reply, _, _ = wire.request(
            self._sock, {"op": "committed", "topic": self.topic,
                         "group": self.group})
        self._next = reply["offset"] + 1  # resume AFTER the committed one

    def _ensure(self) -> None:
        if self._sock is None:
            if self._next is not None:  # not the first connect: a drop
                self.reconnects += 1
                _reconnects.inc()
                _flight_recorder().record(
                    "broker_reconnect", topic=self.topic, group=self.group,
                    n=self.reconnects)
            self._connect()

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # lint: swallowed-exception-ok (socket already dead is why we are here)
                pass
            self._sock = None

    # ------------------------------------------------------- queue protocol
    def get(self, timeout: float = 0.05):
        """Next message as (meta, {name: array}); raises queue.Empty when the
        log is exhausted within ``timeout`` (Route's poll contract)."""
        deadline = time.time() + timeout
        while True:
            try:
                self._ensure()
                reply, payload, _ = wire.request(
                    self._sock,
                    {"op": "fetch", "topic": self.topic,
                     "offset": self._next,
                     "max_wait_s": max(0.0, deadline - time.time())})
            except (ConnectionError, OSError, RuntimeError):
                self._drop()
                if time.time() >= deadline:
                    raise queue.Empty from None
                # exponential backoff to a cap while the broker stays down;
                # reset to the base interval as soon as data flows again
                time.sleep(min(self._cur_backoff,
                               max(0.0, deadline - time.time())))
                self._cur_backoff = min(self._cur_backoff * 2.0,
                                        self._backoff_cap)
                continue
            if reply.get("eof"):
                raise queue.Empty
            meta = reply["meta"]
            if self._native_decode:
                arrays = _decode_arrays_native(meta.get("arrays", []),
                                               payload)
            else:
                arrays = wire.unpack_arrays(meta.get("arrays", []), payload)
            self._cur_backoff = self._backoff  # data flowed: reset backoff
            self._delivered = reply["offset"]
            self._last_delivered = reply["offset"]
            self._next = reply["offset"] + 1
            ref = _parse_traceparent(meta.get(TRACEPARENT_HEADER))
            if ref is not None:
                # the consume hop of the cross-process trace: parented on
                # the producer's publish span, finished immediately (the
                # handling work gets its own child spans via
                # last_trace_ref)
                csp = _start_span("broker.consume", parent=ref,
                                  topic=self.topic, group=self.group,
                                  offset=reply["offset"])
                csp.finish()
                self.last_trace_ref = csp.ref()
            else:
                self.last_trace_ref = None
            with self.all_tasks_done:
                self.unfinished_tasks += 1
            return meta, arrays

    def task_done(self) -> None:
        offset, self._delivered = self._delivered, None
        if offset is not None:
            try:
                self._ensure()
                wire.request(self._sock,
                             {"op": "commit", "topic": self.topic,
                              "group": self.group, "offset": offset})
            except (ConnectionError, OSError, RuntimeError):
                # commit lost with the connection: the message redelivers
                # after reconnect (at-least-once), never silently skipped
                self._drop()
        with self.all_tasks_done:
            if self.unfinished_tasks > 0:
                self.unfinished_tasks -= 1
            if not self.unfinished_tasks:
                self.all_tasks_done.notify_all()

    def commit_delivered(self) -> Optional[int]:
        """Commit the highest offset delivered so far, without the
        ``task_done`` bookkeeping — the elastic worker's window-commit: it
        calls this only after a push window lands on the PS, so a crash
        redelivers at most one window's worth of batches (at-least-once,
        duplicates bounded by the commit cadence). Returns the committed
        offset, or None if nothing was delivered yet. A lost commit is
        deliberately NOT retried here: redelivery is the safe direction."""
        offset = self._last_delivered
        if offset is None:
            return None
        try:
            self._ensure()
            wire.request(self._sock,
                         {"op": "commit", "topic": self.topic,
                          "group": self.group, "offset": offset})
        except (ConnectionError, OSError, RuntimeError):
            # commit lost with the connection: the window redelivers after
            # the replacement reconnects (at-least-once, never skipped)
            self._drop()
            return None
        return offset

    def close(self) -> None:
        self._drop()


def _decode_arrays_native(metas: List[dict], payload) -> Dict[str, np.ndarray]:
    """Consumer-side decode through the native ingest decoder (off-GIL
    bytes -> f32); any array the native path can't take (missing .so, exotic
    dtype, ragged length) falls back to the pure-Python wire decode —
    result parity is bitwise either way."""
    from deeplearning4j_tpu import nativert as _nrt
    view = wire._byteview(payload) if len(payload) else memoryview(b"")
    out, off = {}, 0
    for meta in metas:
        n = meta["nbytes"]
        chunk = view[off:off + n]
        off += n
        dec = None
        if meta.get("dtype") == "float32":
            codec = {"none": "f32", "bf16": "bf16"}.get(meta.get("codec"))
            if codec is not None:
                dec = _nrt.decode_records(chunk, codec)
        if dec is None:
            out[meta["name"]] = wire.decode_array(meta, chunk)
        else:
            out[meta["name"]] = dec.reshape(tuple(meta["shape"]))
    return out


class BrokerIngestSource:
    """Iterable over a consumer subscription's array messages, shaped for
    ``datasets.prefetch.DevicePrefetcher``: construct with
    ``native_decode=True`` on the consumer and hand this to the prefetcher —
    records then travel broker -> native off-GIL decode -> staged device
    batch with the training step overlapping both. Iteration ends at a
    ``fin``-marked message or after ``idle_timeout_s`` with no data."""

    def __init__(self, consumer: "ReconnectingConsumer",
                 idle_timeout_s: float = 5.0):
        self._consumer = consumer
        self._idle_timeout_s = float(idle_timeout_s)

    def __iter__(self):
        idle_deadline = time.time() + self._idle_timeout_s
        while True:
            try:
                meta, arrays = self._consumer.get(timeout=0.25)
            except queue.Empty:
                if time.time() >= idle_deadline:
                    return
                continue
            idle_deadline = time.time() + self._idle_timeout_s
            if meta.get("fin"):
                self._consumer.task_done()
                return
            self._consumer.task_done()
            yield arrays


class BrokerTrainingRoute(Route):
    """Online training fed by the broker: (x, y) array messages from a
    (topic, group) subscription -> model.fit — the networked equivalent of
    streaming.TrainingRoute, surviving broker connection drops."""

    def __init__(self, model, addr: Tuple[str, int], topic: str,
                 group: str = "train"):
        self.model = model
        super().__init__(ReconnectingConsumer(addr, topic, group),
                         self._train)

    def _train(self, msg) -> None:
        _, arrays = msg
        self.model.fit(np.asarray(arrays["x"], np.float32),
                       np.asarray(arrays["y"], np.float32))

    def stop(self) -> None:
        super().stop()
        self.source.close()
