"""Streaming routes: online training and model serving over queues.

Reference: dl4j-streaming (SURVEY.md §2.4) — Camel+Kafka routes feeding
online training/serving (`CamelKafkaRouteBuilder`, `DL4jServeRouteBuilder`).
The TPU-native equivalent keeps the route abstraction but replaces the
Camel/Kafka transport with in-process bounded queues: a ``Route`` consumes
messages on a background thread and hands them to the model. A Kafka-style
broker maps onto the same ``Route`` API by replacing the queue with a
consumer poll loop — the seam is `source.get()`.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import ROUTE_ERRORS_TOTAL

_route_errors = _obs_registry().counter(
    ROUTE_ERRORS_TOTAL, "handler exceptions swallowed by streaming routes, "
                        "by route class")


class Route:
    """A consume loop on a background thread (reference Camel route)."""

    def __init__(self, source: "queue.Queue", handler: Callable[[Any], None]):
        self.source = source
        self.handler = handler
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.processed = 0
        self.errors: List[str] = []
        self._err_series = _route_errors.labels(route=type(self).__name__)

    def start(self) -> "Route":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.source.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self.handler(msg)
                self.processed += 1
            except Exception as e:  # route keeps consuming — but loudly:
                # the errors list alone made a poisoned route invisible to
                # dashboards; count it and leave a flight-recorder breadcrumb
                self.errors.append(f"{type(e).__name__}: {e}")
                self._err_series.inc()
                _flight_recorder().record(
                    "route_error", route=type(self).__name__,
                    error=f"{type(e).__name__}: {e}")
            finally:
                self.source.task_done()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def drain(self, timeout: float = 30.0) -> None:
        """Block until every queued message has been fully handled (not just
        popped — uses the queue's task accounting, so a handler mid-fit still
        counts as pending)."""
        deadline = time.time() + timeout
        with self.source.all_tasks_done:
            while self.source.unfinished_tasks and time.time() < deadline:
                self.source.all_tasks_done.wait(0.05)


class TrainingRoute(Route):
    """Online training: (features, labels) messages -> model.fit (reference
    CamelKafkaRouteBuilder feeding training)."""

    def __init__(self, model, capacity: int = 64):
        self.model = model
        super().__init__(queue.Queue(maxsize=capacity), self._train)

    def _train(self, msg) -> None:
        x, y = msg
        self.model.fit(np.asarray(x, np.float32), np.asarray(y, np.float32))

    def send(self, features, labels, timeout: float = 10.0) -> None:
        self.source.put((features, labels), timeout=timeout)


class ServingRoute(Route):
    """Model serving: feature messages -> predictions on the output queue
    (reference DL4jServeRouteBuilder)."""

    def __init__(self, model, capacity: int = 64):
        self.model = model
        self.output: "queue.Queue" = queue.Queue()
        super().__init__(queue.Queue(maxsize=capacity), self._serve)

    def _serve(self, msg) -> None:
        request_id, features = msg
        out = self.model.output(np.asarray(features, np.float32))
        self.output.put((request_id, np.asarray(out)))

    def send(self, request_id, features, timeout: float = 10.0) -> None:
        self.source.put((request_id, features), timeout=timeout)

    def receive(self, timeout: float = 10.0):
        return self.output.get(timeout=timeout)
