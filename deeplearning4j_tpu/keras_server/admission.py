"""Admission control: bounded pending work, fail-fast overload.

A serving box melts down when it queues unboundedly — latency grows without
limit and every request eventually times out. The controller caps the
number of admitted-but-unfinished requests; past the cap, ``admit()``
raises :class:`RejectedError` immediately and the HTTP layer maps it to
``429 Too Many Requests`` with a ``Retry-After`` hint. The queue-depth
gauge (``dl4j_serve_queue_depth``) is updated on BOTH edges so the metric
always agrees with what a 429 claims (pinned by tests/test_serving.py).

**Priority-aware shedding.** Requests may carry a ``priority`` tag
(``low`` < ``normal`` < ``high``); each priority sees a *fraction* of the
pending budget (:data:`PRIORITY_FLOORS`). When the queue fills past a
priority's floor, that priority is refused while higher priorities keep
admitting — under saturation the fleet sheds low-priority tenants first
and a high-priority request only ever sees a 429 when the queue is
genuinely full. Untagged traffic defaults to ``high`` so legacy callers
keep the full budget. Priority sheds (refusals below the hard cap) are
accounted per tenant in ``dl4j_serve_shed_total{tenant,priority}`` on top
of the blanket ``dl4j_serve_rejected_total``.
"""
from __future__ import annotations

import threading

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.tracing import trace_span

#: recognized priority tags, lowest first (shed order under saturation)
PRIORITY_LEVELS = ("low", "normal", "high")

#: fraction of ``max_pending`` each priority may fill before it is shed;
#: ``high`` owns the whole budget, so a high-priority 429 means the queue
#: is hard-full, not priority-shed
PRIORITY_FLOORS = {"low": 0.5, "normal": 0.75, "high": 1.0}


def normalize_priority(priority) -> str:
    """Map an untrusted tag (HTTP header) onto a known level; unknown or
    missing tags get the full budget (``high``) — shedding is opt-in."""
    p = str(priority).strip().lower() if priority else "high"
    return p if p in PRIORITY_FLOORS else "high"


class RejectedError(RuntimeError):
    """Request refused at admission (maps to HTTP 429)."""

    def __init__(self, pending: int, limit: int, retry_after_s: float,
                 priority: str = "high", shed: bool = False):
        super().__init__(
            f"serving queue full ({pending}/{limit} pending); "
            f"retry in ~{retry_after_s:.3f}s")
        self.pending = pending
        self.limit = limit
        self.retry_after_s = retry_after_s
        #: priority the refused request carried
        self.priority = priority
        #: True when the refusal was a priority shed (queue had room above
        #: this priority's floor), False when the queue was hard-full
        self.shed = shed


class AdmissionController:
    """Counting semaphore with metrics and a Retry-After estimate."""

    def __init__(self, max_pending: int = 256,
                 expected_latency_s: float = 0.05, metrics=None):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.max_pending = int(max_pending)
        self.expected_latency_s = float(expected_latency_s)
        self._lock = threading.Lock()
        self._pending = 0
        self.rejected = 0
        self.shed = 0
        m = metrics or global_registry()
        self._g_depth = m.gauge(
            _n.SERVE_QUEUE_DEPTH, "admitted-but-unfinished serve requests")
        self._c_rejected = m.counter(
            _n.SERVE_REJECTED_TOTAL, "requests refused at admission (429)")
        self._c_shed = m.counter(
            _n.SERVE_SHED_TOTAL,
            "requests priority-shed at admission, by tenant and priority")

    @property
    def pending(self) -> int:
        with self._lock:
            return self._pending

    def limit_for(self, priority: str) -> int:
        """The pending budget ``priority`` may fill before it is shed."""
        floor = PRIORITY_FLOORS.get(priority, 1.0)
        return max(1, int(self.max_pending * floor))

    def admit(self, n: int = 1, priority: str = "high",
              tenant: str = "-") -> None:
        """Admit ``n`` requests or raise :class:`RejectedError`. The
        decision is a trace span: accepted requests record the depth they
        entered at, rejects stamp ``status="rejected"`` — the tail sampler
        always keeps rejected traces."""
        limit = self.limit_for(priority)
        with trace_span("admission") as sp:
            with self._lock:
                if self._pending + n > limit:
                    shed = limit < self.max_pending
                    self.rejected += n
                    self._c_rejected.inc(n)
                    if shed:
                        self.shed += n
                        self._c_shed.labels(
                            tenant=tenant, priority=priority).inc(n)
                    sp.set_status("rejected")
                    sp.set_attr(pending=self._pending, limit=limit,
                                priority=priority)
                    # crude but honest: a full queue drains one expected-
                    # latency per slot; clients treat it as a floor, not a
                    # promise
                    raise RejectedError(self._pending, limit,
                                        self.expected_latency_s,
                                        priority=priority, shed=shed)
                self._pending += n
                self._g_depth.set(self._pending)
                sp.set_attr(pending=self._pending, limit=limit)

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._pending = max(0, self._pending - n)
            self._g_depth.set(self._pending)
