"""Admission control: bounded pending work, fail-fast overload.

A serving box melts down when it queues unboundedly — latency grows without
limit and every request eventually times out. The controller caps the
number of admitted-but-unfinished requests; past the cap, ``admit()``
raises :class:`RejectedError` immediately and the HTTP layer maps it to
``429 Too Many Requests`` with a ``Retry-After`` hint. The queue-depth
gauge (``dl4j_serve_queue_depth``) is updated on BOTH edges so the metric
always agrees with what a 429 claims (pinned by tests/test_serving.py).
"""
from __future__ import annotations

import threading

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.tracing import trace_span


class RejectedError(RuntimeError):
    """Request refused at admission (maps to HTTP 429)."""

    def __init__(self, pending: int, limit: int, retry_after_s: float):
        super().__init__(
            f"serving queue full ({pending}/{limit} pending); "
            f"retry in ~{retry_after_s:.3f}s")
        self.pending = pending
        self.limit = limit
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Counting semaphore with metrics and a Retry-After estimate."""

    def __init__(self, max_pending: int = 256,
                 expected_latency_s: float = 0.05, metrics=None):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.max_pending = int(max_pending)
        self.expected_latency_s = float(expected_latency_s)
        self._lock = threading.Lock()
        self._pending = 0
        self.rejected = 0
        m = metrics or global_registry()
        self._g_depth = m.gauge(
            _n.SERVE_QUEUE_DEPTH, "admitted-but-unfinished serve requests")
        self._c_rejected = m.counter(
            _n.SERVE_REJECTED_TOTAL, "requests refused at admission (429)")

    @property
    def pending(self) -> int:
        with self._lock:
            return self._pending

    def admit(self, n: int = 1) -> None:
        """Admit ``n`` requests or raise :class:`RejectedError`. The
        decision is a trace span: accepted requests record the depth they
        entered at, rejects stamp ``status="rejected"`` — the tail sampler
        always keeps rejected traces."""
        with trace_span("admission") as sp:
            with self._lock:
                if self._pending + n > self.max_pending:
                    self.rejected += n
                    self._c_rejected.inc(n)
                    sp.set_status("rejected")
                    sp.set_attr(pending=self._pending,
                                limit=self.max_pending)
                    # crude but honest: a full queue drains one expected-
                    # latency per slot; clients treat it as a floor, not a
                    # promise
                    raise RejectedError(self._pending, self.max_pending,
                                        self.expected_latency_s)
                self._pending += n
                self._g_depth.set(self._pending)
                sp.set_attr(pending=self._pending, limit=self.max_pending)

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._pending = max(0, self._pending - n)
            self._g_depth.set(self._pending)
