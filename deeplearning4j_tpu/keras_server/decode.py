"""Continuous (iteration-level) batching for autoregressive decode.

The MicroBatcher (batcher.py) coalesces independent one-shot forwards —
right for classify/score traffic, wrong for generation: under
request-level batching a batch runs until its LONGEST sequence finishes,
so one long session holds every slot hostage and steady-state occupancy
collapses. This engine batches at the **iteration** level instead (the
ORCA scheduling model): ONE persistent decode step compiled per capacity
bucket runs every iteration over a fixed-capacity slot tensor; sessions
are admitted into free slots BETWEEN steps and evicted the step their
sequence ends, so the device batch stays full while individual sessions
churn.

What makes admission cheap is the state layout, grown from
``StreamSessions``' parked-state idiom into preallocated device-resident
**per-slot state blocks**:

- transformer, ``kv="dense"``: a KV cache ``[cap, max_context, heads,
  head_dim]`` per block, written at the slot's position each step and
  attention-masked to ``j <= position`` — a freed slot's stale keys are
  unreachable by construction, so admission never touches the cache;
- transformer, ``kv="paged"``: the SAME logical cache resolved through a
  per-slot page table over one fixed physical page pool
  ``[n_pages + 1, page_size, heads, head_dim]`` per block (paging.py).
  A slot consumes pages only for tokens it has written, so session count
  decouples from the context ceiling; sessions whose prompts share a
  prefix map the same physical pages copy-on-write (fork-on-write inside
  the compiled step), and refcounted pages return to the free list on
  eviction. The step scatters this iteration's k/v through the table,
  gathers the logical view back (ops/paged_attention.py), and runs the
  IDENTICAL masked attention math — the dense program is the bitwise
  oracle at every capacity bucket;
- LSTM (the PR 6 recurrent engine): ``h``/``c`` blocks ``[cap, hidden]``
  per layer, zeroed INSIDE the compiled step for slots flagged ``fresh``
  — admission is a host-side slot write, never a recompile.

Prompt prefill feeds prompt tokens one per step through the SAME compiled
program (teacher forcing; emitted tokens are discarded until the last
prompt token is consumed), so prompt length is not a compile axis: the
only compiles are the capacity buckets (powers of two, grown on demand),
pinned by tests/test_decode.py as ``compile count == bucket count``.

**Speculative decoding** (``draft_net=``): the teacher-forcing prefill
path generalizes to a T-token verify program — the same per-token math
unrolled ``spec_tokens + 1`` times in one dispatch. A small draft model
pinned alongside (same ModelRegistry) proposes ``spec_tokens`` tokens
per round; the target verifies all of them in ONE dispatch and accepts
the longest argmax-agreeing prefix, rolling its position back past the
first mismatch (rejected writes sit at ``j > position`` — stale by the
same masking invariant that free slot reuse relies on). Because
acceptance compares greedy argmax to greedy argmax, the emitted stream
is bitwise identical to plain greedy decode at ANY acceptance rate; the
win is dispatch amortization (and, on real hardware, HBM read reuse).

``mode="static"`` runs the SAME compiled step but only admits when every
slot has drained — the request-level baseline for the A/B in
scripts/serve_load.jsonl. Because per-slot math is row-independent (the
bitwise padding property test_serving.py pins for the MLP path), a
session's token stream is bitwise identical under either schedule.

Sampling is greedy argmax on device: deterministic, so continuous-vs-
static equality is exact, and the int8-vs-bf16 drift bound (ops/quant.py)
is measurable on the returned per-token distributions.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common import get_policy
from deeplearning4j_tpu.nn.conf.layers.attention import TransformerBlock
from deeplearning4j_tpu.nn.conf.layers.feedforward import EmbeddingLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    GravesBidirectionalLSTM, LSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.profiler import (
    note_dispatch as _profile_note_dispatch,
)
from deeplearning4j_tpu.observability.tracing import (
    NOOP_SPAN, global_trace_store, start_span,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.ops.paged_attention import paged_gather
from deeplearning4j_tpu.ops.quant import (
    dequantize_tree, gather_rows, quantize_tree, quantized_matmul,
    tree_param_bytes,
)

from .admission import RejectedError
from .paging import (TRASH_PAGE, PagePool, alloc_dense_kv, alloc_page_pool)

#: the compiled-program name of the persistent step — the compile tracker
#: records one event per capacity bucket under it (tests filter on this);
#: paged / draft / verify variants suffix it, so the filter still matches
DECODE_PROGRAM_NAME = "decode_step"

DECODE_MODES = ("continuous", "static")
DECODE_KV = ("dense", "paged")

#: occupancy fraction at which the engine starts background-compiling the
#: NEXT capacity bucket (continuous mode; growth would otherwise compile
#: synchronously mid-step the moment backlog arrives)
_PREWARM_OCCUPANCY = 0.75

log = logging.getLogger(__name__)


def _copy_tree(tree):
    return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)


def _streaming_lstm(layer) -> bool:
    return isinstance(layer, LSTM) and not isinstance(
        layer, GravesBidirectionalLSTM)


class DecodeSession:
    """One generation request: a prompt plus a token budget.

    The engine appends generated tokens (and their host timestamps) as they
    materialize; ``result()`` blocks until eviction. ``t_sched`` is the
    OFFERED arrival time when the caller runs an open-loop schedule — TTFT
    is measured from it so a backed-up engine cannot hide queueing delay
    (no coordinated omission).
    """

    _next_sid = [0]
    _sid_lock = threading.Lock()

    def __init__(self, prompt, max_new_tokens: int,
                 t_sched: Optional[float] = None, stream=None):
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("prompt must contain at least one token id")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._sid_lock:
            self._next_sid[0] += 1
            self.sid = self._next_sid[0]
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.stream = stream
        self.tokens: List[int] = []        #: generated token ids
        self.token_times: List[float] = []  #: host perf_counter per token
        self.probs: List[np.ndarray] = []   #: per-token dists (opt-in)
        self.t_submit = time.perf_counter()
        self.t_sched = self.t_submit if t_sched is None else float(t_sched)
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.evict_reason: Optional[str] = None
        self.done = threading.Event()
        # engine-internal slot bookkeeping
        self._prompt_idx = 0
        #: spec-decode stream history: every input token the target has
        #: consumed or will consume next (prompt + accepted emissions)
        self._hist: List[int] = []
        # request-trace spans, owned across threads via the session object
        # (contextvars do not follow the pump thread); all no-ops when
        # tracing is disabled
        self._span = NOOP_SPAN   #: decode.queue — submit -> admit
        self._span_phase = None  #: decode.prefill, then decode.decode
        self._span_park = None   #: open page-starvation episode
        #: per-session spec-decode tallies (stamped on the decode span)
        self._spec_proposed = 0
        self._spec_accepted = 0

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None else self.t_first - self.t_sched

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"session {self.sid} not finished within {timeout}s")
        return self.tokens


# --------------------------------------------------------------- step builders
def _build_lstm_step(conf, quant: Optional[str], vocab: int):
    """Per-iteration step for LSTM stacks: one-hot the slot tokens, thread
    ``{h, c}`` slot blocks through ``apply_streaming`` (the PR 6 engine),
    zeroing state for ``fresh`` slots inside the program."""
    layers = conf.layers

    def step(params_list, state_list, blocks, tokens, fresh, positions):
        if quant == "int8":
            params_list = dequantize_tree(params_list)
        h = jax.nn.one_hot(tokens, vocab, dtype=jnp.float32)[:, None, :]
        new_blocks = []
        for i, layer in enumerate(layers):
            pp = conf.preprocessor(i)
            if pp is not None:
                h = pp.pre_process(h)
            if _streaming_lstm(layer):
                st = {
                    "h": jnp.where(fresh[:, None], 0.0, blocks[i]["h"]),
                    "c": jnp.where(fresh[:, None], 0.0, blocks[i]["c"]),
                }
                h, rs = layer.apply_streaming(params_list[i], st, h)
                new_blocks.append(rs)
            else:
                h, _ = layer.apply(params_list[i], state_list[i], h,
                                   train=False, rng=None)
                new_blocks.append(blocks[i])
        probs = h[:, -1, :]
        return jnp.argmax(probs, axis=-1).astype(jnp.int32), probs, new_blocks

    return step


class _DenseKV:
    """Dense cache adapter: write this step's k/v at each slot's position
    (the ``jnp.where`` one-hot row update), read back the stored block.
    THE oracle layout — the paged adapter must be bitwise-equal to it."""

    def __init__(self, blocks):
        self.blocks = list(blocks)

    def write_read(self, i, k, v, positions):
        K, V = self.blocks[i]["k"], self.blocks[i]["v"]
        tmax = K.shape[1]
        at_pos = (jnp.arange(tmax)[None, :]
                  == positions[:, None])[..., None, None]
        K = jnp.where(at_pos, k[:, None], K)
        V = jnp.where(at_pos, v[:, None], V)
        self.blocks[i] = {"k": K, "v": V}
        return K, V


class _PagedKV:
    """Paged cache adapter: scatter this step's k/v into the physical pool
    through the slot's page-table row, then gather the logical
    ``[cap, max_context, H, D]`` view back for attention. Positions at or
    past the context ceiling (including the parking sentinel) redirect
    the write to the trash page; the gathered garbage beyond a slot's
    mapped pages sits at ``j > position`` where the mask never looks —
    identical values to the dense block wherever the mask CAN look, which
    is what makes the two layouts bitwise-interchangeable."""

    def __init__(self, blocks, table, page_size):
        self.blocks = list(blocks)
        self.table = table
        self.page_size = page_size

    def write_read(self, i, k, v, positions):
        ps = self.page_size
        pool_k, pool_v = self.blocks[i]["k"], self.blocks[i]["v"]
        cap, P = self.table.shape
        in_range = positions < P * ps
        pidx = jnp.clip(positions // ps, 0, P - 1)
        rows = self.table[jnp.arange(cap), pidx]
        # active slots own their write page exclusively (the CoW planner's
        # invariant), so scatter indices never collide except on trash —
        # where every colliding row carries identical (garbage) values
        wp = jnp.where(in_range, rows, TRASH_PAGE)
        off = jnp.where(in_range, positions % ps, 0)
        pool_k = pool_k.at[wp, off].set(k)
        pool_v = pool_v.at[wp, off].set(v)
        self.blocks[i] = {"k": pool_k, "v": pool_v}
        K = paged_gather(pool_k, self.table)
        V = paged_gather(pool_v, self.table)
        return K, V


def _fork_pages(blocks, fork_src, fork_dst):
    """Apply this iteration's copy-on-write forks: one gather+scatter per
    pool copies page ``fork_src[c]`` onto ``fork_dst[c]`` for every slot
    (non-forking slots carry trash→trash, a self-copy of garbage). Runs
    BEFORE any write so a forked slot's history is in place when its
    write lands in the fresh page."""
    out = []
    for b in blocks:
        if b and "k" in b:
            out.append({"k": b["k"].at[fork_dst].set(b["k"][fork_src]),
                        "v": b["v"].at[fork_dst].set(b["v"][fork_src])})
        else:
            out.append(b)
    return out


def _tf_validate(conf):
    for i in range(len(conf.layers)):
        if conf.preprocessor(i) is not None:
            raise ValueError(
                "decode does not support preprocessors in transformer "
                "stacks; got one before layer "
                f"{i} ({type(conf.layers[i]).__name__})")


def _tf_forward(layers, params_list, tokens, positions, kv):
    """ONE token through the transformer stack for every slot — the shared
    core of the single-token step, the paged step and the T-token verify
    program. Keeping the math in one function is what makes the
    paged-vs-dense and spec-vs-greedy bitwise contracts hold by
    construction: every variant runs these exact ops, only
    ``kv.write_read`` differs (and it is pure data movement)."""
    pol = get_policy()
    od, cd = pol.output_dtype, pol.compute_dtype
    cap = tokens.shape[0]
    x = None
    for i, layer in enumerate(layers):
        p = params_list[i]
        if isinstance(layer, EmbeddingLayer):
            x = (gather_rows(p["W"], tokens) + p["b"]).astype(od)
            x = layer.act_fn()(x)
        elif isinstance(layer, TransformerBlock):
            F = layer.n_out
            H = layer.n_heads
            D = F // H
            h = TransformerBlock._ln(x, p["ln1_g"], p["ln1_b"])
            qkv = quantized_matmul(h.astype(cd), p["Wqkv"],
                                   compute_dtype=cd)
            q, k, v = jnp.split(qkv.astype(od), 3, axis=-1)
            q = q.reshape(cap, H, D)
            k = k.reshape(cap, H, D)
            v = v.reshape(cap, H, D)
            K, V = kv.write_read(i, k, v, positions)
            tmax = K.shape[1]
            # a freed slot's stale cache rows sit at j > position of the
            # next tenant, so masking to j <= position doubles as the
            # admission reset — no cache zeroing on slot reuse
            valid = (jnp.arange(tmax)[None, None, :]
                     <= positions[:, None, None])
            s = jnp.einsum("chd,cthd->cht", q.astype(jnp.float32),
                           K.astype(jnp.float32)) / jnp.sqrt(
                               jnp.float32(D))
            s = jnp.where(valid, s, jnp.float32(-1e30))
            w = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("cht,cthd->chd", w,
                           V.astype(jnp.float32)).reshape(cap, F)
            att = quantized_matmul(o.astype(cd), p["Wo"],
                                   compute_dtype=cd)
            x = x + att.astype(od) + p["bo"].astype(od)
            h = TransformerBlock._ln(x, p["ln2_g"], p["ln2_b"])
            h = quantized_matmul(h.astype(cd), p["W1"], compute_dtype=cd)
            h = jax.nn.gelu(h.astype(od) + p["b1"].astype(od))
            h = quantized_matmul(h.astype(cd), p["W2"], compute_dtype=cd)
            x = x + h.astype(od) + p["b2"].astype(od)
        elif isinstance(layer, RnnOutputLayer):
            logits = quantized_matmul(x.astype(cd), p["W"],
                                      compute_dtype=cd)
            x = layer.act_fn()(logits.astype(od) + p["b"].astype(od))
        else:
            raise ValueError(
                f"decode cannot stream layer {type(layer).__name__}")
    return jnp.argmax(x, axis=-1).astype(jnp.int32), x


def _build_transformer_step(conf, quant: Optional[str], vocab: int,
                            page_size: Optional[int] = None):
    """Per-iteration step for decoder-only transformer stacks: embed the
    slot tokens, write this step's k/v into each block's cache at the
    slot position, attend the single query over ``j <= position``, finish
    with the time-distributed output head. Matmuls that dominate the step
    route through :func:`ops.quant.quantized_matmul` so the int8 policy is
    dequant-free where the Pallas path allows. ``page_size`` switches the
    cache layout to the paged plane (extra table/fork args)."""
    layers = conf.layers
    _tf_validate(conf)

    if page_size is None:
        def step(params_list, state_list, blocks, tokens, fresh, positions):
            kv = _DenseKV(blocks)
            tok, probs = _tf_forward(layers, params_list, tokens,
                                     positions, kv)
            return tok, probs, kv.blocks
    else:
        def step(params_list, state_list, blocks, tokens, fresh, positions,
                 table, fork_src, fork_dst):
            blocks = _fork_pages(blocks, fork_src, fork_dst)
            kv = _PagedKV(blocks, table, page_size)
            tok, probs = _tf_forward(layers, params_list, tokens,
                                     positions, kv)
            return tok, probs, kv.blocks

    return step


def _build_transformer_verify(conf, quant: Optional[str], vocab: int,
                              T: int, page_size: Optional[int] = None):
    """The T-token spec-decode verify program: the single-token core
    unrolled T times in ONE dispatch (teacher forcing over the proposed
    tokens — PR 11's prefill path as a batched program). Token t writes
    KV at ``position + t`` and emits the argmax continuation, so the
    per-position outputs are the same ops in the same order as T separate
    single-token dispatches — bitwise equality with plain greedy decode
    is by construction, acceptance only decides which outputs count."""
    layers = conf.layers
    _tf_validate(conf)

    def _unroll(params_list, blocks, tokens, positions, kv):
        outs, prbs = [], []
        for t in range(T):
            tok, pr = _tf_forward(layers, params_list, tokens[:, t],
                                  positions + t, kv)
            outs.append(tok)
            prbs.append(pr)
        return jnp.stack(outs, axis=1), jnp.stack(prbs, axis=1)

    if page_size is None:
        def step(params_list, state_list, blocks, tokens, fresh, positions):
            kv = _DenseKV(blocks)
            outs, prbs = _unroll(params_list, blocks, tokens, positions, kv)
            return outs, prbs, kv.blocks
    else:
        def step(params_list, state_list, blocks, tokens, fresh, positions,
                 table, fork_src, fork_dst):
            blocks = _fork_pages(blocks, fork_src, fork_dst)
            kv = _PagedKV(blocks, table, page_size)
            outs, prbs = _unroll(params_list, blocks, tokens, positions, kv)
            return outs, prbs, kv.blocks

    return step


class DecodeEngine:
    """Persistent decode loop with slot-level admission/eviction.

    ``submit()`` queues a session; one daemon pump thread admits, steps and
    evicts. ``mode="continuous"`` admits into any free slot between steps;
    ``mode="static"`` (the request-level baseline) admits only when the
    whole batch has drained. ``quant="int8"`` pins the engine's parameter
    snapshot under the int8 serving DtypePolicy (ops/quant.py).

    ``kv="paged"`` (transformer only) swaps the dense per-slot KV blocks
    for the paged memory plane: ``n_pages`` physical pages of
    ``page_size`` tokens each, shared copy-on-write across sessions with
    equal prompt prefixes. ``draft_net`` (transformer only) enables
    speculative decoding: ``spec_tokens`` proposals per round from the
    draft, verified by the target in one multi-token dispatch.
    """

    def __init__(self, net, *, max_context: int = 128, min_slots: int = 2,
                 max_slots: int = 16, eos_id: Optional[int] = None,
                 mode: str = "continuous", quant: Optional[str] = None,
                 capture_probs: bool = False, max_queue: int = 4096,
                 metrics=None, kv: str = "dense", page_size: int = 16,
                 n_pages: Optional[int] = None, draft_net=None,
                 spec_tokens: int = 3):
        if mode not in DECODE_MODES:
            raise ValueError(f"mode must be one of {DECODE_MODES}, "
                             f"got {mode!r}")
        if kv not in DECODE_KV:
            raise ValueError(f"kv must be one of {DECODE_KV}, got {kv!r}")
        if not (1 <= min_slots <= max_slots):
            raise ValueError("need 1 <= min_slots <= max_slots")
        net._require_init()
        conf = net.conf
        out = conf.layers[-1]
        if not isinstance(out, RnnOutputLayer):
            raise ValueError(
                "decode needs a time-distributed output head "
                f"(RnnOutputLayer), got {type(out).__name__}")
        self.vocab = int(out.n_out)
        first = conf.layers[0]
        if int(first.n_in) != self.vocab:
            raise ValueError(
                f"decode feeds outputs back as inputs: first-layer n_in "
                f"{first.n_in} must equal output vocab {self.vocab}")
        has_tf = any(isinstance(l, TransformerBlock) for l in conf.layers)
        has_lstm = any(_streaming_lstm(l) for l in conf.layers)
        if has_tf and has_lstm:
            raise ValueError("decode supports pure-LSTM or pure-transformer "
                             "stacks, not a mix")
        if not (has_tf or has_lstm):
            raise ValueError(
                "decode needs a stateful sequence model (LSTM stack or "
                "TransformerBlock stack)")
        if any(isinstance(l, GravesBidirectionalLSTM) for l in conf.layers):
            raise ValueError("bidirectional LSTMs cannot stream "
                             "(the backward pass needs the full sequence)")
        self.kind = "transformer" if has_tf else "lstm"
        self.mode = mode
        self.max_context = int(max_context)
        self.min_slots = int(min_slots)
        self.max_slots = int(max_slots)
        self.eos_id = eos_id
        self.capture_probs = bool(capture_probs)
        self.quant = "int8" if quant == "int8" else None
        self.kv = kv
        self.page_size = int(page_size)
        self._net = net
        self._conf = conf
        # ---- paged memory plane ----
        self._pool: Optional[PagePool] = None
        if kv == "paged":
            if self.kind != "transformer":
                raise ValueError(
                    "kv='paged' needs a transformer stack (LSTM state is "
                    "h/c vectors, not a KV cache)")
            if self.page_size < 1 or self.max_context % self.page_size:
                raise ValueError(
                    f"max_context {self.max_context} must be a multiple of "
                    f"page_size {self.page_size}")
            self._pages_per_slot = self.max_context // self.page_size
            if n_pages is None:
                # capacity parity with the dense layout at max_slots
                n_pages = self.max_slots * self._pages_per_slot
            if int(n_pages) < 1:
                raise ValueError("n_pages must be >= 1")
            self._n_pages = int(n_pages)
            self._pool = PagePool(self._n_pages, self.page_size)
        # ---- speculative decoding ----
        self._spec_draft = None
        self.spec_tokens = int(spec_tokens)
        if draft_net is not None:
            if self.kind != "transformer":
                raise ValueError("speculative decoding needs a transformer "
                                 "target (the verify program is the "
                                 "teacher-forcing prefill path)")
            draft_net._require_init()
            dconf = draft_net.conf
            dout = dconf.layers[-1]
            if not isinstance(dout, RnnOutputLayer) \
                    or int(dout.n_out) != self.vocab:
                raise ValueError(
                    "draft model must share the target's vocab "
                    f"({self.vocab}) and end in an RnnOutputLayer")
            if not any(isinstance(l, TransformerBlock)
                       for l in dconf.layers):
                raise ValueError("draft model must be a transformer stack")
            if self.spec_tokens < 1:
                raise ValueError("spec_tokens must be >= 1")
            self._spec_draft = draft_net
            self._draft_conf = dconf
        # pinned snapshot, exactly like PredictFn: a later fit() on `net`
        # donates its own buffers, never these
        self._params = _copy_tree(net.params_list)
        self._states = _copy_tree(net.state_list)
        if self.quant == "int8":
            self._params = quantize_tree(self._params)
        ps_arg = self.page_size if kv == "paged" else None
        extra = (("kv", self.kv, "page_size", self.page_size,
                  "n_pages", self._n_pages) if kv == "paged" else ())
        suffix = ("+int8" if self.quant else "") \
            + (":paged" if kv == "paged" else "")
        # blocks (arg 2) are donated: the step updates every slot cache in
        # place instead of allocating a second copy of the KV blocks
        self._step = self._draft_step = self._verify_step = None
        if self._spec_draft is None:
            builder = (_build_lstm_step if self.kind == "lstm"
                       else _build_transformer_step)
            if self.kind == "lstm":
                fn = builder(conf, self.quant, self.vocab)
            else:
                fn = builder(conf, self.quant, self.vocab, page_size=ps_arg)
            self._step = net._jit(DECODE_PROGRAM_NAME + suffix, fn,
                                  donate=(2,), extra=extra)
        else:
            self._verify_T = self.spec_tokens + 1
            self._verify_step = net._jit(
                DECODE_PROGRAM_NAME + suffix + f":verify{self._verify_T}",
                _build_transformer_verify(conf, self.quant, self.vocab,
                                          self._verify_T, page_size=ps_arg),
                donate=(2,), extra=extra + ("spec", self._verify_T))
            self._draft_params = _copy_tree(draft_net.params_list)
            self._draft_states = _copy_tree(draft_net.state_list)
            self._draft_step = draft_net._jit(
                DECODE_PROGRAM_NAME + ":draft",
                _build_transformer_step(self._draft_conf, None, self.vocab),
                donate=(2,))
        m = metrics or global_registry()
        self._g_occupancy = m.gauge(
            _n.SERVE_SLOT_OCCUPANCY,
            "active decode slots / slot capacity of the last step")
        self._h_growth_stall = m.histogram(
            _n.SERVE_BUCKET_GROWTH_STALL_SECONDS,
            "first-step dispatch time of each new capacity bucket (the "
            "live-traffic stall growth causes; pre-warmed buckets show "
            "steady-state step time here)")
        self._h_ttft = m.histogram(
            _n.SERVE_TTFT_SECONDS,
            "offered-arrival to first generated token")
        self._c_tokens = m.counter(
            _n.SERVE_TOKENS_TOTAL, "generated tokens streamed to sessions")
        self._c_evictions = m.counter(
            _n.SERVE_EVICTIONS_TOTAL, "slot evictions by reason")
        self._g_pages = m.gauge(
            _n.DECODE_PAGES_IN_USE,
            "physical KV pages currently mapped by live slots")
        self._g_share = m.gauge(
            _n.DECODE_PREFIX_SHARE_RATIO,
            "prompt tokens served from shared prefix pages / prompt "
            "tokens admitted (cumulative)")
        self._g_accept = m.gauge(
            _n.DECODE_SPEC_ACCEPTANCE,
            "spec-decode proposals accepted / proposals offered "
            "(cumulative)")
        self._c_spec = m.counter(
            _n.DECODE_SPEC_TOKENS_TOTAL,
            "spec-decode draft proposals by verify outcome")
        self._c_copy = m.counter(
            _n.DECODE_STATE_COPY_BYTES_TOTAL,
            "host bytes copied moving per-slot decode state across "
            "capacity buckets (device block moves are a single on-device "
            "scatter and do not count)")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self.max_queue = int(max_queue)
        self._closed = False
        self._cap = 0
        self._slots: List[Optional[DecodeSession]] = []
        self._tokens_h = np.zeros((0,), np.int32)
        self._pos_h = np.zeros((0,), np.int32)
        self._fresh_h = np.zeros((0,), bool)
        self._table_h = np.zeros((0, 0), np.int32)
        self._fork_src_h = np.zeros((0,), np.int32)
        self._fork_dst_h = np.zeros((0,), np.int32)
        self._park_h = np.zeros((0,), bool)
        self._dpos_h = np.zeros((0,), np.int32)
        self._blocks = None
        self._draft_blocks = None
        self._copy_bytes = 0
        self._grow_to(self.min_slots)
        self._steps = 0
        self._generated = 0
        self._evicted = 0
        self._occupancy_sum = 0.0
        self._peak_active = 0
        self._shared_tokens = 0
        self._prompt_tokens = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._buckets: set = set()
        #: capacity buckets a background pre-warm has been started for
        self._warming: set = set()
        self._thread = threading.Thread(
            target=self._loop, name="serve-decode-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- slot state
    def _zero_blocks(self, cap: int):
        """Preallocated per-slot state blocks for one capacity bucket.
        Paged pools are capacity-INdependent: every bucket shares the one
        physical pool, so this allocates fresh pools only for pre-warm
        probes (the live pool rides ``self._blocks``)."""
        blocks = []
        for layer in self._conf.layers:
            if self.kind == "lstm" and _streaming_lstm(layer):
                h = int(layer.n_out)
                blocks.append(
                    {"h": jnp.zeros((cap, h), jnp.float32),
                     "c": jnp.zeros((cap, h), jnp.float32)})
            elif self.kind == "transformer" \
                    and isinstance(layer, TransformerBlock):
                hd = int(layer.n_out) // int(layer.n_heads)
                if self.kv == "paged":
                    blocks.append(alloc_page_pool(
                        self._n_pages, self.page_size,
                        int(layer.n_heads), hd))
                else:
                    blocks.append(alloc_dense_kv(
                        cap, self.max_context, int(layer.n_heads), hd))
            else:
                blocks.append({})
        return blocks

    def _zero_draft_blocks(self, cap: int):
        blocks = []
        for layer in self._draft_conf.layers:
            if isinstance(layer, TransformerBlock):
                hd = int(layer.n_out) // int(layer.n_heads)
                blocks.append(alloc_dense_kv(
                    cap, self.max_context, int(layer.n_heads), hd))
            else:
                blocks.append({})
        return blocks

    #: requires-lock: _cond
    def _grow_to(self, cap: int) -> None:
        """Move to a larger capacity bucket. Dense blocks move with ONE
        device-side scatter per leaf (``.at[:old].set`` — never a host
        round-trip per slot); the paged pool is capacity-independent and
        moves nothing. What the host DOES copy (slot arrays, page tables)
        is billed to ``dl4j_decode_state_copy_bytes_total``."""
        old = self._cap
        self._slots += [None] * (cap - old)
        copied = 0
        for name_ in ("_tokens_h", "_pos_h", "_fresh_h", "_fork_src_h",
                      "_fork_dst_h", "_park_h", "_dpos_h"):
            a = getattr(self, name_)
            grown = np.zeros((cap,), a.dtype)
            grown[:old] = a
            copied += a.nbytes
            setattr(self, name_, grown)
        if self._pool is not None:
            t = np.full((cap, self._pages_per_slot), TRASH_PAGE, np.int32)
            if old:
                t[:old] = self._table_h
            copied += self._table_h.nbytes
            self._table_h = t
            if self._blocks is None:
                self._blocks = self._zero_blocks(cap)
        else:
            new_blocks = self._zero_blocks(cap)
            if self._blocks is not None and old:
                new_blocks = jax.tree_util.tree_map(
                    lambda z, a: z.at[:old].set(a), new_blocks, self._blocks)
            self._blocks = new_blocks
        if self._spec_draft is not None:
            new_draft = self._zero_draft_blocks(cap)
            if self._draft_blocks is not None and old:
                new_draft = jax.tree_util.tree_map(
                    lambda z, a: z.at[:old].set(a), new_draft,
                    self._draft_blocks)
            self._draft_blocks = new_draft
        self._cap = cap
        self._copy_bytes += copied
        self._c_copy.inc(copied)

    # --------------------------------------------------------------- producer
    def submit(self, prompt, max_new_tokens: int = 32,
               t_sched: Optional[float] = None,
               stream=None) -> DecodeSession:
        """Queue one generation session; returns immediately."""
        sess = DecodeSession(prompt, max_new_tokens, t_sched=t_sched,
                             stream=stream)
        # parented under the ambient span (the HTTP handler's root) on
        # THIS thread; the pump finishes it cross-thread via the session
        sess._span = start_span("decode.queue", sid=sess.sid,
                                prompt_len=len(sess.prompt),
                                max_new=sess.max_new_tokens)
        try:
            bad = [t for t in sess.prompt if not 0 <= t < self.vocab]
            if bad:
                raise ValueError(f"prompt token ids {bad} outside vocab "
                                 f"[0, {self.vocab})")
            if self._pool is not None:
                span = min(len(sess.prompt) + sess.max_new_tokens,
                           self.max_context)
                worst = -(-span // self.page_size)
                if worst > self._n_pages:
                    # the session can NEVER fit this pool — fail fast with
                    # the 429 the HTTP layer already maps, not a
                    # mid-decode OOM
                    raise RejectedError(worst, self._n_pages, 60.0)
            with self._cond:
                if self._closed:
                    raise RuntimeError("DecodeEngine is closed")
                if len(self._queue) >= self.max_queue:
                    # Retry-After: the backlog drains roughly a session
                    # per slot per active session's remaining budget; 1s
                    # is the honest coarse answer at this layer
                    raise RejectedError(len(self._queue), self.max_queue,
                                        1.0)
                self._queue.append(sess)
                self._cond.notify()
        except RejectedError:
            sess._span.set_status("rejected").finish()
            raise
        except Exception:
            sess._span.set_status("error").finish()
            raise
        return sess

    # ----------------------------------------------------------------- pump
    def _active_count(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    #: requires-lock: _cond
    def _admit_locked(self) -> None:
        """Under the lock: move queued sessions into free slots.

        Continuous mode admits whenever a slot is free; static mode admits
        only into a fully-drained batch (the request-level baseline). Both
        grow the capacity bucket (a new compile, power-of-two) when demand
        outruns the current one. Paged engines additionally gate on free
        pages (FIFO — no head-of-line bypass) and map any registered
        prefix pages copy-on-write before the first step."""
        active = self._active_count()
        if self.mode == "static" and active:
            return
        while self._queue and active >= self._cap \
                and self._cap < self.max_slots:
            self._grow_to(min(self._cap * 2, self.max_slots))
        for i in range(self._cap):
            if not self._queue:
                break
            if self._slots[i] is not None:
                continue
            sess = self._queue[0]
            skip = 0
            if self._pool is not None:
                pids, covered = self._pool.match_prompt(sess.prompt)
                ps = self.page_size
                fresh_pages = (-(-len(sess.prompt) // ps)) - len(pids) \
                    + (1 if covered % ps else 0)
                if self._pool.free_pages < fresh_pages + 1:
                    break
                for k, pid in enumerate(pids):
                    self._pool.incref(pid)
                    self._table_h[i, k] = pid
                skip = min(covered, len(sess.prompt) - 1)
                self._shared_tokens += skip
                self._prompt_tokens += len(sess.prompt)
            self._queue.popleft()
            self._slots[i] = sess
            self._tokens_h[i] = sess.prompt[skip]
            self._pos_h[i] = skip
            self._fresh_h[i] = True
            sess._prompt_idx = skip
            sess._span.set_attr(slot=i, skip=skip)
            sess._span.finish()
            sess._span_phase = start_span(
                "decode.prefill", parent=self._span_parent(sess),
                sid=sess.sid, prompt_len=len(sess.prompt), skip=skip)
            if self._spec_draft is not None:
                sess._hist = list(sess.prompt)
                self._dpos_h[i] = 0
            active += 1
        if self._prompt_tokens:
            self._g_share.set(self._shared_tokens / self._prompt_tokens)
        self._peak_active = max(self._peak_active, active)

    #: requires-lock: _cond
    def _release_pages_locked(self, i: int) -> None:
        row = self._table_h[i]
        for pid in {int(x) for x in row.tolist()} - {TRASH_PAGE}:
            self._pool.decref(pid)
        row[:] = TRASH_PAGE

    @staticmethod
    def _span_parent(sess):
        """The session's queue span as a parent, or None so a real span
        never parents under the no-op singleton's empty trace id."""
        return sess._span if sess._span is not NOOP_SPAN else None

    #: requires-lock: _cond
    def _trace_evict_locked(self, sess, reason: str) -> None:
        """Close the session's open spans at eviction: preemption emits an
        instant ``decode.preempt`` span so the victim's trace names why it
        ended mid-stream, step errors flip the phase span's status (the
        tail sampler then always keeps the trace)."""
        if sess._span_park is not None:
            sess._span_park.set_attr(evicted=True)
            sess._span_park.finish()
            sess._span_park = None
        if reason == "pool_exhausted":
            start_span("decode.preempt", parent=self._span_parent(sess),
                       sid=sess.sid).finish()
        sp = sess._span_phase
        if sp is not None:
            if reason == "error":
                sp.set_status("error")
            sp.set_attr(reason=reason, tokens=len(sess.tokens))
            if sess._spec_proposed:
                sp.set_attr(spec_proposed=sess._spec_proposed,
                            spec_accepted=sess._spec_accepted)
            sp.finish()
            sess._span_phase = None
        sess._span.finish()  # idempotent; covers never-admitted paths

    #: requires-lock: _cond
    def _evict_locked(self, i: int, reason: str) -> None:
        sess = self._slots[i]
        self._slots[i] = None
        if self._pool is not None:
            self._release_pages_locked(i)
        self._evicted += 1
        self._c_evictions.labels(reason=reason).inc()
        self._trace_evict_locked(sess, reason)
        sess.evict_reason = reason
        sess.t_done = time.perf_counter()
        sess.done.set()

    # -------------------------------------------------------- page planning
    #: requires-lock: _cond
    def _map_window_locked(self, i: int, window: int) -> bool:
        """Ensure slot ``i`` owns pages for its next ``window`` write
        positions: allocate unmapped pages, copy-on-write-fork shared
        ones. False = exhaustion (caller parks or preempts); partial
        allocations stay mapped — they are owned, a retry reuses them."""
        pool, ps = self._pool, self.page_size
        pos = int(self._pos_h[i])
        for t in range(window):
            q = pos + t
            if q >= self.max_context:
                break  # clamped to the trash page in-step
            k = q // ps
            pid = int(self._table_h[i, k])
            if pid == TRASH_PAGE:
                npid = pool.alloc()
                if npid is None:
                    return False
                self._table_h[i, k] = npid
            elif pool.refcount(pid) > 1:
                npid = pool.alloc()
                if npid is None:
                    return False
                if q % ps:
                    # mid-page: earlier offsets hold this slot's live
                    # history — device-copies src→dst inside the step.
                    # Only the FIRST window page can be shared (sharing
                    # covers written positions only), so the single
                    # fork-per-slot register never collides; park if a
                    # second copy somehow arises rather than lose one.
                    if int(self._fork_dst_h[i]) != TRASH_PAGE:
                        pool.decref(npid)
                        return False
                    self._fork_src_h[i] = pid
                    self._fork_dst_h[i] = npid
                pool.decref(pid)
                self._table_h[i, k] = npid
        return True

    #: requires-lock: _cond
    def _plan_pages_locked(self, window: int) -> None:
        """Map every active slot's write window; on total exhaustion (no
        slot can move) preempt the YOUNGEST tenant so the rest make
        progress — pool pressure degrades to parking, never to OOM."""
        self._fork_src_h[:] = TRASH_PAGE
        self._fork_dst_h[:] = TRASH_PAGE
        self._park_h[:] = False
        pending = [i for i in range(self._cap)
                   if self._slots[i] is not None]
        any_live = False
        while True:
            still = []
            for i in pending:
                if self._map_window_locked(i, window):
                    any_live = True
                else:
                    still.append(i)
            if any_live or not still:
                for i in still:
                    self._park_h[i] = True
                break
            victim = max(still, key=lambda i: self._slots[i].sid)
            self._evict_locked(victim, "pool_exhausted")
            pending = [i for i in still if i != victim]
            if not pending:
                break
        # park-episode spans: one span per contiguous starved stretch, so
        # a trace shows exactly when pool pressure stalled the session
        for i in range(self._cap):
            sess = self._slots[i]
            if sess is None:
                continue
            if self._park_h[i]:
                if sess._span_park is None:
                    sess._span_park = start_span(
                        "decode.park", parent=self._span_parent(sess),
                        sid=sess.sid, reason="pool_exhausted")
            elif sess._span_park is not None:
                sess._span_park.finish()
                sess._span_park = None
        self._g_pages.set(self._pool.pages_in_use)

    #: requires-lock: _cond
    def _register_prefix_locked(self, i: int, sess, lo: int,
                                hi: int) -> None:
        """Publish the prompt pages slot ``i`` finished writing in
        ``[lo, hi)`` so later sessions can map them copy-on-write.
        Generated positions are never registered — sharing is a prompt
        (system-prefix) property."""
        ps = self.page_size
        for q in range(lo, min(hi, len(sess.prompt))):
            self._pool.register(sess.prompt[:q + 1],
                                int(self._table_h[i, q // ps]))

    def _note_first_token(self, sess, ttft: float) -> None:
        """Prefill -> decode phase flip on the session's trace, plus the
        TTFT exemplar so a burning TTFT SLO can name this trace."""
        sp = sess._span_phase
        if sp is None:
            return
        sp.set_attr(ttft_s=round(ttft, 6))
        sp.finish()
        sess._span_phase = start_span(
            "decode.decode", parent=self._span_parent(sess), sid=sess.sid)
        if sp.trace_id:
            global_trace_store().put_exemplar(
                _n.SERVE_TTFT_SECONDS, ttft, sp.trace_id)

    def _pump_once(self) -> bool:
        """One admit/step/bookkeep iteration; False when idle-and-closed."""
        if self._spec_draft is not None:
            return self._pump_once_spec()
        return self._pump_once_single()

    def _pump_once_single(self) -> bool:
        with self._cond:
            while True:
                self._admit_locked()
                if self._active_count():
                    break
                if self._closed and not self._queue:
                    return False
                self._cond.wait(0.05)
            cap = self._cap
            if self._pool is not None:
                self._plan_pages_locked(1)
            active = [(i, self._slots[i]) for i in range(cap)
                      if self._slots[i] is not None]
            if not active:
                return True  # planning preempted the whole batch
            parked = self._park_h.copy() if self._pool is not None else None
            tokens = jnp.asarray(self._tokens_h)
            fresh = jnp.asarray(self._fresh_h)
            pos_np = self._pos_h.copy()
            if parked is not None:
                # parked slots write the trash page and advance nothing:
                # the sentinel position clamps their scatter out of range
                pos_np[parked] = self.max_context
            positions = jnp.asarray(pos_np)
            paged_args = ()
            if self._pool is not None:
                paged_args = (jnp.asarray(self._table_h),
                              jnp.asarray(self._fork_src_h),
                              jnp.asarray(self._fork_dst_h))
            blocks = self._blocks
            growing = cap not in self._buckets
        t0 = time.perf_counter()
        try:
            next_tok, probs, new_blocks = self._step(
                self._params, self._states, blocks, tokens, fresh,
                positions, *paged_args)
            next_h = np.asarray(next_tok)  # lint: host-sync-in-hot-loop-ok (the emitted token drives admission/eviction and feeds back as the next input; the sync IS the iteration boundary)
            probs_h = np.asarray(probs) if self.capture_probs else None
        except Exception as e:
            if growing:
                # evict-all is not the only signal a failed growth leaves:
                # this event names the bucket that never came up
                _flight_recorder().record(
                    "decode_bucket_growth_failed", cap=cap, mode=self.mode,
                    error=repr(e))
            _flight_recorder().dump(
                reason="decode-step-error",
                extra={"cap": cap, "mode": self.mode, "error": repr(e)})
            with self._cond:
                for i, sess in active:
                    self._evict_locked(i, "error")
            raise
        dt = time.perf_counter() - t0
        if growing:
            # first step at a new capacity: with a cold cache this dispatch
            # carries the XLA compile (the stall); warm it is step-sized
            self._h_growth_stall.labels(bucket=str(cap)).observe(dt)
        now = time.perf_counter()
        prewarm_cap = None
        with self._cond:
            self._blocks = new_blocks
            self._steps += 1
            self._buckets.add(cap)
            occupancy = len(active) / cap
            if (self.mode == "continuous" and cap < self.max_slots
                    and occupancy >= _PREWARM_OCCUPANCY):
                nxt = min(cap * 2, self.max_slots)
                if nxt not in self._buckets and nxt not in self._warming:
                    self._warming.add(nxt)
                    prewarm_cap = nxt
            self._occupancy_sum += occupancy
            n_steps = self._steps
            for i, sess in active:
                if parked is not None and parked[i]:
                    continue  # wrote trash; retry when pages free up
                p0 = int(self._pos_h[i])
                self._fresh_h[i] = False
                self._pos_h[i] += 1
                if self._pool is not None:
                    self._register_prefix_locked(i, sess, p0, p0 + 1)
                prefilling = sess._prompt_idx < len(sess.prompt) - 1
                if prefilling:
                    sess._prompt_idx += 1
                    self._tokens_h[i] = sess.prompt[sess._prompt_idx]
                else:
                    tok = int(next_h[i])
                    sess.tokens.append(tok)
                    sess.token_times.append(now)
                    if probs_h is not None:
                        sess.probs.append(probs_h[i].copy())
                    if sess.t_first is None:
                        sess.t_first = now
                        self._h_ttft.observe(now - sess.t_sched)
                        self._note_first_token(sess, now - sess.t_sched)
                    self._generated += 1
                    self._c_tokens.inc()
                    if sess.stream is not None:
                        sess.stream(sess.sid, tok, now)
                    if self.eos_id is not None and tok == self.eos_id:
                        self._evict_locked(i, "eos")
                        continue
                    if len(sess.tokens) >= sess.max_new_tokens:
                        self._evict_locked(i, "max_tokens")
                        continue
                    self._tokens_h[i] = tok
                if self.kind == "transformer" \
                        and self._pos_h[i] >= self.max_context:
                    self._evict_locked(i, "context")
        self._g_occupancy.set(occupancy)
        # a decode iteration advances the step clock like a fit/serve
        # dispatch: bucket-growth compiles are expected, steady-state
        # compiles are what the storm detector must catch
        _compile_tracker().note_step()
        _profile_note_dispatch(dt)
        _wd_beat(n_steps)
        if prewarm_cap is not None:
            threading.Thread(
                target=self._prewarm, args=(prewarm_cap,),
                name="serve-decode-prewarm", daemon=True).start()
        return True

    # ------------------------------------------------------------ spec pump
    def _pump_once_spec(self) -> bool:
        """One speculative round: γ draft proposals, one T-token verify
        dispatch, accept the longest argmax-agreeing prefix. Prefill rides
        the same round — prompt tokens are guaranteed-accept inputs — so
        the worst case (acceptance 0) degrades to exactly the plain
        engine's one token per dispatch, never below."""
        gamma = self.spec_tokens
        T = self._verify_T
        with self._cond:
            while True:
                self._admit_locked()
                if self._active_count():
                    break
                if self._closed and not self._queue:
                    return False
                self._cond.wait(0.05)
            cap = self._cap
            if self._pool is not None:
                self._plan_pages_locked(T)
            active = [(i, self._slots[i]) for i in range(cap)
                      if self._slots[i] is not None]
            if not active:
                return True
            parked = (self._park_h.copy() if self._pool is not None
                      else np.zeros((cap,), bool))
            paged_args = ()
            if self._pool is not None:
                paged_args = (jnp.asarray(self._table_h),
                              jnp.asarray(self._fork_src_h),
                              jnp.asarray(self._fork_dst_h))
            d0 = self._dpos_h.copy()
            base_pos = self._pos_h.copy()
            fresh = jnp.asarray(self._fresh_h)
            growing = cap not in self._buckets
        live = [(i, s) for i, s in active if not parked[i]]
        t0 = time.perf_counter()
        try:
            # ---- draft phase: γ single-token dispatches ----
            props = {i: {} for i, _ in active}   # stream index -> proposal
            dins = {i: [] for i, _ in active}    # tokens the draft consumed
            dcur = d0.copy()
            zeros_b = jnp.zeros((cap,), bool)
            for _ in range(gamma):
                dtok = np.zeros((cap,), np.int32)
                for i, s in live:
                    c = int(dcur[i])
                    tok = s._hist[c] if c < len(s._hist) else props[i][c]
                    dtok[i] = tok
                    dins[i].append(tok)
                dpos = dcur.copy()
                dpos[parked] = self.max_context
                # lint: lockguard-ok (KV blocks are pump-thread-confined: only the single pump thread touches them; _grow_to's locked writes run on that same thread)
                dout, _, self._draft_blocks = self._draft_step(
                    self._draft_params, self._draft_states,
                    self._draft_blocks, jnp.asarray(dtok), zeros_b,
                    jnp.asarray(dpos))
                dout_h = np.asarray(dout)  # lint: host-sync-in-hot-loop-ok (the proposal feeds the draft's own next input; the sync is the draft's iteration boundary)
                for i, s in live:
                    c = int(dcur[i])
                    if c + 1 >= len(s._hist):
                        props[i][c + 1] = int(dout_h[i])
                    dcur[i] = c + 1
            # ---- verify phase: one T-token dispatch ----
            vtok = np.zeros((cap, T), np.int32)
            trusted = {}
            for i, s in live:
                p = int(base_pos[i])
                row = []
                for t in range(T):
                    sidx = p + t
                    if sidx < len(s._hist):
                        vtok[i, t] = s._hist[sidx]
                        row.append(True)
                    elif sidx in props[i]:
                        vtok[i, t] = props[i][sidx]
                        row.append(False)
                    else:
                        # draft still catching up: pad (always rejected —
                        # the write rolls back behind the position mask)
                        vtok[i, t] = s._hist[-1]
                        row.append(None)
                trusted[i] = row
            vpos = base_pos.copy()
            vpos[parked] = self.max_context
            # lint: lockguard-ok (KV blocks are pump-thread-confined: only the single pump thread touches them; _grow_to's locked writes run on that same thread)
            outs, vprobs, self._blocks = self._verify_step(
                self._params, self._states, self._blocks,
                jnp.asarray(vtok), fresh, jnp.asarray(vpos), *paged_args)
            outs_h = np.asarray(outs)  # lint: host-sync-in-hot-loop-ok (accept/reject drives eviction and the next round's inputs; the sync IS the round boundary)
            vprobs_h = np.asarray(vprobs) if self.capture_probs else None
        except Exception as e:
            if growing:
                _flight_recorder().record(
                    "decode_bucket_growth_failed", cap=cap, mode=self.mode,
                    error=repr(e))
            _flight_recorder().dump(
                reason="decode-step-error",
                extra={"cap": cap, "mode": self.mode, "error": repr(e)})
            with self._cond:
                for i, sess in active:
                    self._evict_locked(i, "error")
            raise
        dt = time.perf_counter() - t0
        if growing:
            self._h_growth_stall.labels(bucket=str(cap)).observe(dt)
        now = time.perf_counter()
        prewarm_cap = None
        with self._cond:
            self._steps += 1
            self._buckets.add(cap)
            occupancy = len(active) / cap
            if (self.mode == "continuous" and cap < self.max_slots
                    and occupancy >= _PREWARM_OCCUPANCY):
                nxt = min(cap * 2, self.max_slots)
                if nxt not in self._buckets and nxt not in self._warming:
                    self._warming.add(nxt)
                    prewarm_cap = nxt
            self._occupancy_sum += occupancy
            n_steps = self._steps
            for i, s in live:
                p = int(base_pos[i])
                h = s._hist
                row = trusted[i]
                # writes past the context ceiling landed in trash: they
                # can never be accepted, the slot evicts at the ceiling
                max_ok = min(T, self.max_context - p)
                n_ok = max_ok
                # a proposal counts as judged only up to the first reject
                # (everything behind a reject was never on trial), so an
                # identical-weights draft reads acceptance == 1.0 exactly
                proposed = accepted = 0
                for t in range(1, max_ok):
                    if row[t] is True:
                        continue
                    if row[t] is False:
                        proposed += 1
                        if int(vtok[i, t]) == int(outs_h[i, t - 1]):
                            accepted += 1
                            continue
                    n_ok = t
                    break
                evict = None
                for t in range(n_ok):
                    sidx = p + t + 1
                    if sidx < len(h):
                        continue  # teacher-forced prefill output
                    tok = int(outs_h[i, t])
                    h.append(tok)
                    s.tokens.append(tok)
                    s.token_times.append(now)
                    if vprobs_h is not None:
                        s.probs.append(vprobs_h[i, t].copy())
                    if s.t_first is None:
                        s.t_first = now
                        self._h_ttft.observe(now - s.t_sched)
                        self._note_first_token(s, now - s.t_sched)
                    self._generated += 1
                    self._c_tokens.inc()
                    if s.stream is not None:
                        s.stream(s.sid, tok, now)
                    if self.eos_id is not None and tok == self.eos_id:
                        evict = "eos"
                        n_ok = t + 1
                        break
                    if len(s.tokens) >= s.max_new_tokens:
                        evict = "max_tokens"
                        n_ok = t + 1
                        break
                new_p = p + n_ok
                self._spec_proposed += proposed
                self._spec_accepted += accepted
                s._spec_proposed += proposed
                s._spec_accepted += accepted
                if proposed:
                    self._c_spec.labels(outcome="proposed").inc(proposed)
                    self._c_spec.labels(outcome="accepted").inc(accepted)
                # draft keeps KV only for inputs that match the (now
                # settled) true stream; the rest rolls back behind its
                # position mask exactly like the target's rejects
                dvalid = 0
                c0 = int(d0[i])
                for j, tok in enumerate(dins[i]):
                    if c0 + j < len(h) and tok == h[c0 + j]:
                        dvalid += 1
                    else:
                        break
                self._dpos_h[i] = c0 + dvalid
                self._fresh_h[i] = False
                self._pos_h[i] = new_p
                if self._pool is not None and evict is None:
                    self._register_prefix_locked(i, s, p, new_p)
                s._prompt_idx = min(new_p, len(s.prompt) - 1)
                if evict is not None:
                    self._evict_locked(i, evict)
                    continue
                if new_p >= self.max_context:
                    self._evict_locked(i, "context")
                    continue
                self._tokens_h[i] = h[new_p]
            if self._spec_proposed:
                self._g_accept.set(
                    self._spec_accepted / self._spec_proposed)
        self._g_occupancy.set(occupancy)
        _compile_tracker().note_step()
        _profile_note_dispatch(dt)
        _wd_beat(n_steps)
        if prewarm_cap is not None:
            threading.Thread(
                target=self._prewarm, args=(prewarm_cap,),
                name="serve-decode-prewarm", daemon=True).start()
        return True

    def _prewarm_calls(self, cap: int):
        """(program, example-inputs) pairs that cover one capacity bucket
        (single step, or draft + verify for spec engines)."""
        zi = jnp.zeros((cap,), jnp.int32)
        zb = jnp.zeros((cap,), bool)
        paged_args = ()
        if self._pool is not None:
            paged_args = (jnp.zeros((cap, self._pages_per_slot), jnp.int32),
                          zi, zi)
        calls = []
        if self._spec_draft is None:
            calls.append((self._step,
                          (self._params, self._states,
                           self._zero_blocks(cap), zi, zb, zi) + paged_args))
        else:
            calls.append((self._draft_step,
                          (self._draft_params, self._draft_states,
                           self._zero_draft_blocks(cap), zi, zb, zi)))
            vt = jnp.zeros((cap, self._verify_T), jnp.int32)
            calls.append((self._verify_step,
                          (self._params, self._states,
                           self._zero_blocks(cap), vt, zb, zi) + paged_args))
        return calls

    def _prewarm(self, cap: int) -> None:
        """Background-compile the next capacity bucket's step program so
        growth under load does not stall live traffic. Resolves the same
        per-signature entry the pump would, so a concurrent synchronous
        growth dedups on the program's own lock — never a double compile."""
        from deeplearning4j_tpu.nn import compile_cache

        t0 = time.perf_counter()
        try:
            for prog, inputs in self._prewarm_calls(cap):
                warm = getattr(prog, "warm", None)
                if warm is not None:
                    warm(*jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(
                            tuple(a.shape), a.dtype)
                        if hasattr(a, "shape") and hasattr(a, "dtype")
                        else a, inputs))
                else:
                    # kill-switch path (plain jit): one zero step at the
                    # next capacity populates jit's own dispatch cache; the
                    # donated blocks are this thread's private zeros
                    prog(*inputs)
            compile_cache.observe_warmup("decode", time.perf_counter() - t0)
        except Exception as e:
            log.debug("decode pre-warm of bucket %d failed: %r", cap, e)
            with self._lock:
                self._warming.discard(cap)

    def _loop(self) -> None:
        while True:
            try:
                if not self._pump_once():
                    return
            except Exception:
                # sessions in flight were failed by _pump_once; keep serving
                continue

    # ---------------------------------------------------------------- control
    def stats(self) -> dict:
        with self._lock:
            out = {
                "mode": self.mode,
                "kind": self.kind,
                "quant": self.quant,
                "kv": self.kv,
                "capacity": self._cap,
                "max_slots": self.max_slots,
                "buckets": sorted(self._buckets),
                "bucket_count": len(self._buckets),
                "steps": self._steps,
                "tokens": self._generated,
                "evictions": self._evicted,
                "queue_depth": len(self._queue),
                "active": self._active_count(),
                "peak_active": self._peak_active,
                "mean_occupancy": (self._occupancy_sum / self._steps
                                   if self._steps else 0.0),
                "param_bytes": tree_param_bytes(self._params),
                "state_copy_bytes": self._copy_bytes,
            }
            if self._pool is not None:
                out["page_size"] = self.page_size
                out["pool_pages"] = self._n_pages
                out["pages_in_use"] = self._pool.pages_in_use
                out["pages_free"] = self._pool.free_pages
                out["prefix_entries"] = self._pool.prefix_entries
                out["prefix_share_ratio"] = (
                    self._shared_tokens / self._prompt_tokens
                    if self._prompt_tokens else 0.0)
            if self._spec_draft is not None:
                out["spec_tokens"] = self.spec_tokens
                out["spec_proposed"] = self._spec_proposed
                out["spec_accepted"] = self._spec_accepted
                out["spec_acceptance"] = (
                    self._spec_accepted / self._spec_proposed
                    if self._spec_proposed else 0.0)
                out["draft_param_bytes"] = tree_param_bytes(
                    self._draft_params)
            return out

    def state_bytes(self) -> int:
        """Device-resident bytes of the slot state blocks (the number the
        churn regression pins: sessions come and go, this does not grow).
        Paged engines count the fixed pool plus page tables — the
        capacity-independent footprint the ≥2x sessions-per-chip
        acceptance test compares against the dense layout."""
        with self._lock:
            total = tree_param_bytes(self._blocks)
            if self._pool is not None:
                total += self._table_h.nbytes
            if self._draft_blocks is not None:
                total += tree_param_bytes(self._draft_blocks)
            return total

    def idle(self) -> bool:
        """No queued or active sessions — a hot-swapped-away version's
        engine is safe to retire exactly when this is True."""
        with self._lock:
            return not self._queue and not self._active_count()

    def drain(self, timeout_s: float = 60.0) -> None:
        """Block until every queued/active session has finished."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._active_count():
                    return
            time.sleep(0.002)
        raise TimeoutError("decode engine did not drain in time")

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop accepting sessions; the pump drains what is queued first."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout_s)
