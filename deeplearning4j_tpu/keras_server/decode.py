"""Continuous (iteration-level) batching for autoregressive decode.

The MicroBatcher (batcher.py) coalesces independent one-shot forwards —
right for classify/score traffic, wrong for generation: under
request-level batching a batch runs until its LONGEST sequence finishes,
so one long session holds every slot hostage and steady-state occupancy
collapses. This engine batches at the **iteration** level instead (the
ORCA scheduling model): ONE persistent decode step compiled per capacity
bucket runs every iteration over a fixed-capacity slot tensor; sessions
are admitted into free slots BETWEEN steps and evicted the step their
sequence ends, so the device batch stays full while individual sessions
churn.

What makes admission cheap is the state layout, grown from
``StreamSessions``' parked-state idiom into preallocated device-resident
**per-slot state blocks**:

- transformer: a KV cache ``[cap, max_context, heads, head_dim]`` per
  block, written at the slot's position each step and attention-masked to
  ``j <= position`` — a freed slot's stale keys are unreachable by
  construction, so admission never touches the cache;
- LSTM (the PR 6 recurrent engine): ``h``/``c`` blocks ``[cap, hidden]``
  per layer, zeroed INSIDE the compiled step for slots flagged ``fresh``
  — admission is a host-side slot write, never a recompile.

Prompt prefill feeds prompt tokens one per step through the SAME compiled
program (teacher forcing; emitted tokens are discarded until the last
prompt token is consumed), so prompt length is not a compile axis: the
only compiles are the capacity buckets (powers of two, grown on demand),
pinned by tests/test_decode.py as ``compile count == bucket count``.

``mode="static"`` runs the SAME compiled step but only admits when every
slot has drained — the request-level baseline for the A/B in
scripts/serve_load.jsonl. Because per-slot math is row-independent (the
bitwise padding property test_serving.py pins for the MLP path), a
session's token stream is bitwise identical under either schedule.

Sampling is greedy argmax on device: deterministic, so continuous-vs-
static equality is exact, and the int8-vs-bf16 drift bound (ops/quant.py)
is measurable on the returned per-token distributions.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common import get_policy
from deeplearning4j_tpu.nn.conf.layers.attention import TransformerBlock
from deeplearning4j_tpu.nn.conf.layers.feedforward import EmbeddingLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    GravesBidirectionalLSTM, LSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.profiler import (
    note_dispatch as _profile_note_dispatch,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.ops.quant import (
    dequantize_tree, gather_rows, quantize_tree, quantized_matmul,
    tree_param_bytes,
)

from .admission import RejectedError

#: the compiled-program name of the persistent step — the compile tracker
#: records one event per capacity bucket under it (tests filter on this)
DECODE_PROGRAM_NAME = "decode_step"

DECODE_MODES = ("continuous", "static")

#: occupancy fraction at which the engine starts background-compiling the
#: NEXT capacity bucket (continuous mode; growth would otherwise compile
#: synchronously mid-step the moment backlog arrives)
_PREWARM_OCCUPANCY = 0.75

log = logging.getLogger(__name__)


def _copy_tree(tree):
    return jax.tree_util.tree_map(lambda a: jnp.array(a), tree)


def _streaming_lstm(layer) -> bool:
    return isinstance(layer, LSTM) and not isinstance(
        layer, GravesBidirectionalLSTM)


class DecodeSession:
    """One generation request: a prompt plus a token budget.

    The engine appends generated tokens (and their host timestamps) as they
    materialize; ``result()`` blocks until eviction. ``t_sched`` is the
    OFFERED arrival time when the caller runs an open-loop schedule — TTFT
    is measured from it so a backed-up engine cannot hide queueing delay
    (no coordinated omission).
    """

    _next_sid = [0]
    _sid_lock = threading.Lock()

    def __init__(self, prompt, max_new_tokens: int,
                 t_sched: Optional[float] = None, stream=None):
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("prompt must contain at least one token id")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._sid_lock:
            self._next_sid[0] += 1
            self.sid = self._next_sid[0]
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.stream = stream
        self.tokens: List[int] = []        #: generated token ids
        self.token_times: List[float] = []  #: host perf_counter per token
        self.probs: List[np.ndarray] = []   #: per-token dists (opt-in)
        self.t_submit = time.perf_counter()
        self.t_sched = self.t_submit if t_sched is None else float(t_sched)
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.evict_reason: Optional[str] = None
        self.done = threading.Event()
        # engine-internal slot bookkeeping
        self._prompt_idx = 0

    @property
    def ttft_s(self) -> Optional[float]:
        return None if self.t_first is None else self.t_first - self.t_sched

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"session {self.sid} not finished within {timeout}s")
        return self.tokens


# --------------------------------------------------------------- step builders
def _build_lstm_step(conf, quant: Optional[str], vocab: int):
    """Per-iteration step for LSTM stacks: one-hot the slot tokens, thread
    ``{h, c}`` slot blocks through ``apply_streaming`` (the PR 6 engine),
    zeroing state for ``fresh`` slots inside the program."""
    layers = conf.layers

    def step(params_list, state_list, blocks, tokens, fresh, positions):
        if quant == "int8":
            params_list = dequantize_tree(params_list)
        h = jax.nn.one_hot(tokens, vocab, dtype=jnp.float32)[:, None, :]
        new_blocks = []
        for i, layer in enumerate(layers):
            pp = conf.preprocessor(i)
            if pp is not None:
                h = pp.pre_process(h)
            if _streaming_lstm(layer):
                st = {
                    "h": jnp.where(fresh[:, None], 0.0, blocks[i]["h"]),
                    "c": jnp.where(fresh[:, None], 0.0, blocks[i]["c"]),
                }
                h, rs = layer.apply_streaming(params_list[i], st, h)
                new_blocks.append(rs)
            else:
                h, _ = layer.apply(params_list[i], state_list[i], h,
                                   train=False, rng=None)
                new_blocks.append(blocks[i])
        probs = h[:, -1, :]
        return jnp.argmax(probs, axis=-1).astype(jnp.int32), probs, new_blocks

    return step


def _build_transformer_step(conf, quant: Optional[str], vocab: int):
    """Per-iteration step for decoder-only transformer stacks: embed the
    slot tokens, write this step's k/v into each block's slot cache at the
    slot position, attend the single query over ``j <= position``, finish
    with the time-distributed output head. Matmuls that dominate the step
    route through :func:`ops.quant.quantized_matmul` so the int8 policy is
    dequant-free where the Pallas path allows."""
    layers = conf.layers
    for i in range(len(layers)):
        if conf.preprocessor(i) is not None:
            raise ValueError(
                "decode does not support preprocessors in transformer "
                "stacks; got one before layer "
                f"{i} ({type(layers[i]).__name__})")

    def step(params_list, state_list, blocks, tokens, fresh, positions):
        pol = get_policy()
        od, cd = pol.output_dtype, pol.compute_dtype
        cap = tokens.shape[0]
        x = None
        new_blocks = []
        for i, layer in enumerate(layers):
            p = params_list[i]
            if isinstance(layer, EmbeddingLayer):
                x = (gather_rows(p["W"], tokens) + p["b"]).astype(od)
                x = layer.act_fn()(x)
                new_blocks.append(blocks[i])
            elif isinstance(layer, TransformerBlock):
                F = layer.n_out
                H = layer.n_heads
                D = F // H
                h = TransformerBlock._ln(x, p["ln1_g"], p["ln1_b"])
                qkv = quantized_matmul(h.astype(cd), p["Wqkv"],
                                       compute_dtype=cd)
                q, k, v = jnp.split(qkv.astype(od), 3, axis=-1)
                q = q.reshape(cap, H, D)
                k = k.reshape(cap, H, D)
                v = v.reshape(cap, H, D)
                K, V = blocks[i]["k"], blocks[i]["v"]
                tmax = K.shape[1]
                at_pos = (jnp.arange(tmax)[None, :]
                          == positions[:, None])[..., None, None]
                K = jnp.where(at_pos, k[:, None], K)
                V = jnp.where(at_pos, v[:, None], V)
                # a freed slot's stale cache rows sit at j > position of the
                # next tenant, so masking to j <= position doubles as the
                # admission reset — no cache zeroing on slot reuse
                valid = (jnp.arange(tmax)[None, None, :]
                         <= positions[:, None, None])
                s = jnp.einsum("chd,cthd->cht", q.astype(jnp.float32),
                               K.astype(jnp.float32)) / jnp.sqrt(
                                   jnp.float32(D))
                s = jnp.where(valid, s, jnp.float32(-1e30))
                w = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("cht,cthd->chd", w,
                               V.astype(jnp.float32)).reshape(cap, F)
                att = quantized_matmul(o.astype(cd), p["Wo"],
                                       compute_dtype=cd)
                x = x + att.astype(od) + p["bo"].astype(od)
                h = TransformerBlock._ln(x, p["ln2_g"], p["ln2_b"])
                h = quantized_matmul(h.astype(cd), p["W1"], compute_dtype=cd)
                h = jax.nn.gelu(h.astype(od) + p["b1"].astype(od))
                h = quantized_matmul(h.astype(cd), p["W2"], compute_dtype=cd)
                x = x + h.astype(od) + p["b2"].astype(od)
                new_blocks.append({"k": K, "v": V})
            elif isinstance(layer, RnnOutputLayer):
                logits = quantized_matmul(x.astype(cd), p["W"],
                                          compute_dtype=cd)
                x = layer.act_fn()(logits.astype(od) + p["b"].astype(od))
                new_blocks.append(blocks[i])
            else:
                raise ValueError(
                    f"decode cannot stream layer {type(layer).__name__}")
        probs = x
        return jnp.argmax(probs, axis=-1).astype(jnp.int32), probs, new_blocks

    return step


class DecodeEngine:
    """Persistent decode loop with slot-level admission/eviction.

    ``submit()`` queues a session; one daemon pump thread admits, steps and
    evicts. ``mode="continuous"`` admits into any free slot between steps;
    ``mode="static"`` (the request-level baseline) admits only when the
    whole batch has drained. ``quant="int8"`` pins the engine's parameter
    snapshot under the int8 serving DtypePolicy (ops/quant.py).
    """

    def __init__(self, net, *, max_context: int = 128, min_slots: int = 2,
                 max_slots: int = 16, eos_id: Optional[int] = None,
                 mode: str = "continuous", quant: Optional[str] = None,
                 capture_probs: bool = False, max_queue: int = 4096,
                 metrics=None):
        if mode not in DECODE_MODES:
            raise ValueError(f"mode must be one of {DECODE_MODES}, "
                             f"got {mode!r}")
        if not (1 <= min_slots <= max_slots):
            raise ValueError("need 1 <= min_slots <= max_slots")
        net._require_init()
        conf = net.conf
        out = conf.layers[-1]
        if not isinstance(out, RnnOutputLayer):
            raise ValueError(
                "decode needs a time-distributed output head "
                f"(RnnOutputLayer), got {type(out).__name__}")
        self.vocab = int(out.n_out)
        first = conf.layers[0]
        if int(first.n_in) != self.vocab:
            raise ValueError(
                f"decode feeds outputs back as inputs: first-layer n_in "
                f"{first.n_in} must equal output vocab {self.vocab}")
        has_tf = any(isinstance(l, TransformerBlock) for l in conf.layers)
        has_lstm = any(_streaming_lstm(l) for l in conf.layers)
        if has_tf and has_lstm:
            raise ValueError("decode supports pure-LSTM or pure-transformer "
                             "stacks, not a mix")
        if not (has_tf or has_lstm):
            raise ValueError(
                "decode needs a stateful sequence model (LSTM stack or "
                "TransformerBlock stack)")
        if any(isinstance(l, GravesBidirectionalLSTM) for l in conf.layers):
            raise ValueError("bidirectional LSTMs cannot stream "
                             "(the backward pass needs the full sequence)")
        self.kind = "transformer" if has_tf else "lstm"
        self.mode = mode
        self.max_context = int(max_context)
        self.min_slots = int(min_slots)
        self.max_slots = int(max_slots)
        self.eos_id = eos_id
        self.capture_probs = bool(capture_probs)
        self.quant = "int8" if quant == "int8" else None
        self._net = net
        self._conf = conf
        # pinned snapshot, exactly like PredictFn: a later fit() on `net`
        # donates its own buffers, never these
        self._params = _copy_tree(net.params_list)
        self._states = _copy_tree(net.state_list)
        if self.quant == "int8":
            self._params = quantize_tree(self._params)
        builder = (_build_transformer_step if has_tf else _build_lstm_step)
        name = DECODE_PROGRAM_NAME + ("+int8" if self.quant else "")
        # blocks (arg 2) are donated: the step updates every slot cache in
        # place instead of allocating a second copy of the KV blocks
        self._step = net._jit(name, builder(conf, self.quant, self.vocab),
                              donate=(2,))
        m = metrics or global_registry()
        self._g_occupancy = m.gauge(
            _n.SERVE_SLOT_OCCUPANCY,
            "active decode slots / slot capacity of the last step")
        self._h_growth_stall = m.histogram(
            _n.SERVE_BUCKET_GROWTH_STALL_SECONDS,
            "first-step dispatch time of each new capacity bucket (the "
            "live-traffic stall growth causes; pre-warmed buckets show "
            "steady-state step time here)")
        self._h_ttft = m.histogram(
            _n.SERVE_TTFT_SECONDS,
            "offered-arrival to first generated token")
        self._c_tokens = m.counter(
            _n.SERVE_TOKENS_TOTAL, "generated tokens streamed to sessions")
        self._c_evictions = m.counter(
            _n.SERVE_EVICTIONS_TOTAL, "slot evictions by reason")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self.max_queue = int(max_queue)
        self._closed = False
        self._cap = 0
        self._slots: List[Optional[DecodeSession]] = []
        self._tokens_h = np.zeros((0,), np.int32)
        self._pos_h = np.zeros((0,), np.int32)
        self._fresh_h = np.zeros((0,), bool)
        self._blocks = None
        self._grow_to(self.min_slots)
        self._steps = 0
        self._generated = 0
        self._evicted = 0
        self._occupancy_sum = 0.0
        self._buckets: set = set()
        #: capacity buckets a background pre-warm has been started for
        self._warming: set = set()
        self._thread = threading.Thread(
            target=self._loop, name="serve-decode-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- slot state
    def _zero_blocks(self, cap: int):
        """Preallocated per-slot state blocks for one capacity bucket."""
        blocks = []
        for layer in self._conf.layers:
            if self.kind == "lstm" and _streaming_lstm(layer):
                h = int(layer.n_out)
                blocks.append(
                    {"h": jnp.zeros((cap, h), jnp.float32),
                     "c": jnp.zeros((cap, h), jnp.float32)})
            elif self.kind == "transformer" \
                    and isinstance(layer, TransformerBlock):
                hd = int(layer.n_out) // int(layer.n_heads)
                shape = (cap, self.max_context, int(layer.n_heads), hd)
                blocks.append({"k": jnp.zeros(shape, jnp.float32),
                               "v": jnp.zeros(shape, jnp.float32)})
            else:
                blocks.append({})
        return blocks

    def _grow_to(self, cap: int) -> None:
        """Move to a larger capacity bucket: fresh zero blocks with the old
        slots copied in — sessions in flight keep their state and position."""
        old = self._cap
        self._slots += [None] * (cap - old)
        for name_ in ("_tokens_h", "_pos_h", "_fresh_h"):
            a = getattr(self, name_)
            grown = np.zeros((cap,), a.dtype)
            grown[:old] = a
            setattr(self, name_, grown)
        new_blocks = self._zero_blocks(cap)
        if self._blocks is not None and old:
            new_blocks = jax.tree_util.tree_map(
                lambda z, a: z.at[:old].set(a), new_blocks, self._blocks)
        self._blocks = new_blocks
        self._cap = cap

    # --------------------------------------------------------------- producer
    def submit(self, prompt, max_new_tokens: int = 32,
               t_sched: Optional[float] = None,
               stream=None) -> DecodeSession:
        """Queue one generation session; returns immediately."""
        sess = DecodeSession(prompt, max_new_tokens, t_sched=t_sched,
                             stream=stream)
        bad = [t for t in sess.prompt if not 0 <= t < self.vocab]
        if bad:
            raise ValueError(f"prompt token ids {bad} outside vocab "
                             f"[0, {self.vocab})")
        with self._cond:
            if self._closed:
                raise RuntimeError("DecodeEngine is closed")
            if len(self._queue) >= self.max_queue:
                # Retry-After: the backlog drains roughly a session per
                # slot per active session's remaining budget; 1s is the
                # honest coarse answer at this layer
                raise RejectedError(len(self._queue), self.max_queue, 1.0)
            self._queue.append(sess)
            self._cond.notify()
        return sess

    # ----------------------------------------------------------------- pump
    def _active_count(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def _admit_locked(self) -> None:
        """Under the lock: move queued sessions into free slots.

        Continuous mode admits whenever a slot is free; static mode admits
        only into a fully-drained batch (the request-level baseline). Both
        grow the capacity bucket (a new compile, power-of-two) when demand
        outruns the current one.
        """
        active = self._active_count()
        if self.mode == "static" and active:
            return
        while self._queue and active >= self._cap \
                and self._cap < self.max_slots:
            self._grow_to(min(self._cap * 2, self.max_slots))
        for i in range(self._cap):
            if not self._queue:
                break
            if self._slots[i] is not None:
                continue
            sess = self._queue.popleft()
            self._slots[i] = sess
            self._tokens_h[i] = sess.prompt[0]
            self._pos_h[i] = 0
            self._fresh_h[i] = True
            sess._prompt_idx = 0
            active += 1

    def _evict_locked(self, i: int, reason: str) -> None:
        sess = self._slots[i]
        self._slots[i] = None
        self._evicted += 1
        self._c_evictions.labels(reason=reason).inc()
        sess.evict_reason = reason
        sess.t_done = time.perf_counter()
        sess.done.set()

    def _pump_once(self) -> bool:
        """One admit/step/bookkeep iteration; False when idle-and-closed."""
        with self._cond:
            while True:
                self._admit_locked()
                if self._active_count():
                    break
                if self._closed and not self._queue:
                    return False
                self._cond.wait(0.05)
            cap = self._cap
            active = [(i, self._slots[i]) for i in range(cap)
                      if self._slots[i] is not None]
            tokens = jnp.asarray(self._tokens_h)
            fresh = jnp.asarray(self._fresh_h)
            positions = jnp.asarray(self._pos_h)
            blocks = self._blocks
            growing = cap not in self._buckets
        t0 = time.perf_counter()
        try:
            next_tok, probs, new_blocks = self._step(
                self._params, self._states, blocks, tokens, fresh, positions)
            next_h = np.asarray(next_tok)  # lint: host-sync-in-hot-loop-ok (the emitted token drives admission/eviction and feeds back as the next input; the sync IS the iteration boundary)
            probs_h = np.asarray(probs) if self.capture_probs else None
        except Exception as e:
            if growing:
                # evict-all is not the only signal a failed growth leaves:
                # this event names the bucket that never came up
                _flight_recorder().record(
                    "decode_bucket_growth_failed", cap=cap, mode=self.mode,
                    error=repr(e))
            _flight_recorder().dump(
                reason="decode-step-error",
                extra={"cap": cap, "mode": self.mode, "error": repr(e)})
            with self._cond:
                for i, sess in active:
                    self._evict_locked(i, "error")
            raise
        dt = time.perf_counter() - t0
        if growing:
            # first step at a new capacity: with a cold cache this dispatch
            # carries the XLA compile (the stall); warm it is step-sized
            self._h_growth_stall.labels(bucket=str(cap)).observe(dt)
        now = time.perf_counter()
        prewarm_cap = None
        with self._cond:
            self._blocks = new_blocks
            self._steps += 1
            self._buckets.add(cap)
            occupancy = len(active) / cap
            if (self.mode == "continuous" and cap < self.max_slots
                    and occupancy >= _PREWARM_OCCUPANCY):
                nxt = min(cap * 2, self.max_slots)
                if nxt not in self._buckets and nxt not in self._warming:
                    self._warming.add(nxt)
                    prewarm_cap = nxt
            self._occupancy_sum += occupancy
            n_steps = self._steps
            for i, sess in active:
                self._fresh_h[i] = False
                self._pos_h[i] += 1
                prefilling = sess._prompt_idx < len(sess.prompt) - 1
                if prefilling:
                    sess._prompt_idx += 1
                    self._tokens_h[i] = sess.prompt[sess._prompt_idx]
                else:
                    tok = int(next_h[i])
                    sess.tokens.append(tok)
                    sess.token_times.append(now)
                    if probs_h is not None:
                        sess.probs.append(probs_h[i].copy())
                    if sess.t_first is None:
                        sess.t_first = now
                        self._h_ttft.observe(now - sess.t_sched)
                    self._generated += 1
                    self._c_tokens.inc()
                    if sess.stream is not None:
                        sess.stream(sess.sid, tok, now)
                    if self.eos_id is not None and tok == self.eos_id:
                        self._evict_locked(i, "eos")
                        continue
                    if len(sess.tokens) >= sess.max_new_tokens:
                        self._evict_locked(i, "max_tokens")
                        continue
                    self._tokens_h[i] = tok
                if self.kind == "transformer" \
                        and self._pos_h[i] >= self.max_context:
                    self._evict_locked(i, "context")
        self._g_occupancy.set(occupancy)
        # a decode iteration advances the step clock like a fit/serve
        # dispatch: bucket-growth compiles are expected, steady-state
        # compiles are what the storm detector must catch
        _compile_tracker().note_step()
        _profile_note_dispatch(dt)
        _wd_beat(n_steps)
        if prewarm_cap is not None:
            threading.Thread(
                target=self._prewarm, args=(prewarm_cap,),
                name="serve-decode-prewarm", daemon=True).start()
        return True

    def _prewarm(self, cap: int) -> None:
        """Background-compile the next capacity bucket's step program so
        growth under load does not stall live traffic. Resolves the same
        per-signature entry the pump would, so a concurrent synchronous
        growth dedups on the program's own lock — never a double compile."""
        from deeplearning4j_tpu.nn import compile_cache

        t0 = time.perf_counter()
        try:
            inputs = (self._params, self._states, self._zero_blocks(cap),
                      jnp.zeros((cap,), jnp.int32),
                      jnp.zeros((cap,), bool),
                      jnp.zeros((cap,), jnp.int32))
            warm = getattr(self._step, "warm", None)
            if warm is not None:
                warm(*jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                    if hasattr(a, "shape") and hasattr(a, "dtype") else a,
                    inputs))
            else:
                # kill-switch path (plain jit): one zero step at the next
                # capacity populates jit's own dispatch cache; the donated
                # blocks are this thread's private zeros
                self._step(*inputs)
            compile_cache.observe_warmup("decode", time.perf_counter() - t0)
        except Exception as e:
            log.debug("decode pre-warm of bucket %d failed: %r", cap, e)
            with self._lock:
                self._warming.discard(cap)

    def _loop(self) -> None:
        while True:
            try:
                if not self._pump_once():
                    return
            except Exception:
                # sessions in flight were failed by _pump_once; keep serving
                continue

    # ---------------------------------------------------------------- control
    def stats(self) -> dict:
        with self._lock:
            return {
                "mode": self.mode,
                "kind": self.kind,
                "quant": self.quant,
                "capacity": self._cap,
                "max_slots": self.max_slots,
                "buckets": sorted(self._buckets),
                "bucket_count": len(self._buckets),
                "steps": self._steps,
                "tokens": self._generated,
                "evictions": self._evicted,
                "queue_depth": len(self._queue),
                "active": self._active_count(),
                "mean_occupancy": (self._occupancy_sum / self._steps
                                   if self._steps else 0.0),
                "param_bytes": tree_param_bytes(self._params),
            }

    def state_bytes(self) -> int:
        """Device-resident bytes of the slot state blocks (the number the
        churn regression pins: sessions come and go, this does not grow)."""
        with self._lock:
            return tree_param_bytes(self._blocks)

    def idle(self) -> bool:
        """No queued or active sessions — a hot-swapped-away version's
        engine is safe to retire exactly when this is True."""
        with self._lock:
            return not self._queue and not self._active_count()

    def drain(self, timeout_s: float = 60.0) -> None:
        """Block until every queued/active session has finished."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._active_count():
                    return
            time.sleep(0.002)
        raise TimeoutError("decode engine did not drain in time")

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop accepting sessions; the pump drains what is queued first."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout_s)
