"""The decode memory plane: a refcounted physical page pool for KV state.

PR 11's decode engine preallocates dense per-slot KV blocks
``[cap, max_context, H, D]`` — HBM cost scales with capacity × context
ceiling whether or not a slot has written a single token, and that product
is the hard limit on concurrent sessions per chip. This module is the
host half of the paged replacement (the PagedAttention layout, Kwon et
al. SOSP 2023): the device holds ONE fixed physical pool
``[n_pages + 1, page_size, H, D]`` per transformer layer, and each slot
owns only a **page table** row of physical page ids. A slot consumes
pages for tokens it has actually written; eviction returns them to the
free list.

Page id 0 is the **trash page**: never allocated, never mapped into a
live table entry, the scatter target for slots that must not write this
step (inactive slots, pool-exhaustion parking, positions clamped past the
context ceiling). Its contents are garbage by design and unreachable by
design — the ``j <= position`` attention mask never selects an unmapped
page's rows, the same invariant that lets the dense engine skip cache
zeroing on slot reuse.

**Copy-on-write prefix sharing.** Completed *prompt* pages are published
in a prefix registry keyed by the exact token prefix they cover; a
session admitted with a matching prompt maps the same physical pages and
bumps their refcount, skipping that much prefill outright. Any write into
a page with refcount > 1 forks first (device-side page copy inside the
compiled step), so sharers can never observe each other's divergence.
Registry entries die with their page: refcount 0 frees the page AND
drops its keys, so a recycled page can never serve a stale prefix.

All methods assume the caller holds the engine lock (one pump thread plus
admission); the pool itself is deliberately lock-free.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp

#: physical page 0 — the reserved scatter target for suppressed writes;
#: never in the free list, never refcounted, never mapped by a live slot
TRASH_PAGE = 0


class PagePool:
    """Host-side allocator for one engine's physical page pool.

    Tracks the free list, per-page refcounts, and the prompt-prefix
    registry. Device arrays are NOT held here — the engine owns them (they
    ride the compiled step's donated blocks); the pool is pure
    bookkeeping, which is what makes the refcount-leak test cheap.
    """

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 1:
            raise ValueError("page pool needs at least one page")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # LIFO free list: a just-freed (hot) page is reused first
        self._free: List[int] = list(range(self.n_pages, 0, -1))
        self._ref: Dict[int, int] = {}
        self._prefix: Dict[Tuple[int, ...], int] = {}
        self._keys: Dict[int, Set[Tuple[int, ...]]] = {}

    # ------------------------------------------------------------- allocation
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.n_pages - len(self._free)

    def alloc(self) -> Optional[int]:
        """One exclusively-owned page, or ``None`` on exhaustion (the
        caller parks or rejects — an exhausted pool is an admission
        decision, never an OOM)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def incref(self, pid: int) -> None:
        self._ref[pid] += 1

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def decref(self, pid: int) -> bool:
        """Drop one reference; True when this freed the page. Freeing
        drops the page's prefix-registry keys — a recycled page must never
        be reachable under the tokens a previous tenant wrote."""
        n = self._ref[pid] - 1
        if n > 0:
            self._ref[pid] = n
            return False
        del self._ref[pid]
        for key in self._keys.pop(pid, ()):
            if self._prefix.get(key) == pid:
                del self._prefix[key]
        self._free.append(pid)
        return True

    # -------------------------------------------------------- prefix sharing
    def register(self, prefix: Sequence[int], pid: int) -> None:
        """Publish ``pid`` as holding the KV rows for exactly the prompt
        ``prefix`` (the page covers tokens ``[k*page_size, len(prefix))``
        of it). First writer wins: an equal prefix is already backed by an
        equivalent page, and bitwise-equal KV at that (attention state at
        position j is a pure function of tokens[0..j])."""
        key = tuple(int(t) for t in prefix)
        if key in self._prefix:
            return
        self._prefix[key] = pid
        self._keys.setdefault(pid, set()).add(key)

    def match_prompt(self, prompt: Sequence[int]) -> Tuple[List[int], int]:
        """Longest registered prefix of ``prompt``: full pages first, then
        the longest partial tail inside the next page. Returns the page
        chain and the number of prompt tokens it covers; refcounts are NOT
        touched (the caller increfs if it actually maps the chain)."""
        ps = self.page_size
        prompt = [int(t) for t in prompt]
        pids: List[int] = []
        covered = 0
        for k in range(len(prompt) // ps):
            pid = self._prefix.get(tuple(prompt[:(k + 1) * ps]))
            if pid is None:
                break
            pids.append(pid)
            covered = (k + 1) * ps
        tail: Optional[Tuple[int, int]] = None
        for m in range(covered + 1, min(len(prompt), covered + ps) + 1):
            pid = self._prefix.get(tuple(prompt[:m]))
            if pid is not None:
                tail = (pid, m)
        if tail is not None:
            pids.append(tail[0])
            covered = tail[1]
        return pids, covered

    @property
    def prefix_entries(self) -> int:
        return len(self._prefix)


# ----------------------------------------------------------- device allocation
# THE home of raw KV allocation: the dense-kv-alloc lint rule flags
# max_context-sized jnp.zeros anywhere else under keras_server/, so every
# byte of decode state is accounted to one of these two layouts.

def alloc_dense_kv(cap: int, max_context: int, n_heads: int, head_dim: int):
    """One dense per-slot KV block ``[cap, max_context, H, D]`` (k and v)
    — the PR 11 layout, kept as the paged plane's bitwise oracle."""
    shape = (cap, max_context, n_heads, head_dim)
    return {"k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32)}


def alloc_page_pool(n_pages: int, page_size: int, n_heads: int,
                    head_dim: int):
    """One physical page pool ``[n_pages + 1, page_size, H, D]`` (k and
    v); row 0 is the trash page. Allocated ONCE per engine — capacity
    growth never touches it, which is exactly the dense layout's copy cost
    this plane deletes."""
    shape = (n_pages + 1, page_size, n_heads, head_dim)
    return {"k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32)}
