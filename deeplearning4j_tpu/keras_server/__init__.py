"""Keras gateway + the batched serving engine.

Two services live in this package:

- the **training gateway** (below): reference deeplearning4j-keras
  (SURVEY.md §2.8) — a py4j ``GatewayServer`` (keras/Server.java:18)
  exposes ``DeepLearning4jEntryPoint.fit()``
  (DeepLearning4jEntryPoint.java:21), which loads a Keras-exported model
  plus an HDF5 minibatch dataset iterator and trains in the JVM. Here the
  gateway is a newline-delimited-JSON TCP server (py4j's wire role) and
  the entry point drives the TPU training path on the imported network.

- the **serving engine** (registry/batcher/serving/streaming/admission/
  loadgen modules): a versioned :class:`ModelRegistry` pinning non-donated
  compiled predict programs, a :class:`MicroBatcher` coalescing concurrent
  requests into padded power-of-two shape buckets (bounded compile cache),
  and an :class:`InferenceServer` with ``/v1/predict``, 429 backpressure,
  and streaming timestep output over the ``rnnTimeStep`` seam. See
  GUIDE.md "Serving engine".
"""
from __future__ import annotations

import json
import re
import socket
import socketserver
import threading
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.modelimport.hdf5 import H5File


class HDF5MiniBatchDataSetIterator:
    """Iterates a directory of per-batch HDF5 files, each holding one array
    under ``data`` (reference HDF5MiniBatchDataSetIterator.java). Files are
    ordered by the integer in their name (0.h5, 1.h5, ...)."""

    def __init__(self, directory: str, dataset_name: str = "data"):
        self.directory = Path(directory)
        self.dataset_name = dataset_name
        def batch_no(p: Path):
            m = re.search(r"(\d+)", p.stem)
            return int(m.group(1)) if m else 0
        self.files: List[Path] = sorted(
            (p for p in self.directory.iterdir() if p.suffix == ".h5"),
            key=batch_no)
        if not self.files:
            raise FileNotFoundError(f"no .h5 batch files in {directory}")

    def __len__(self) -> int:
        return len(self.files)

    def read(self, i: int) -> np.ndarray:
        with H5File(str(self.files[i])) as f:
            return f.read_dataset(f"/{self.dataset_name}")

    def __iter__(self):
        for i in range(len(self.files)):
            yield self.read(i)


class DeepLearning4jEntryPoint:
    """The RPC surface (reference DeepLearning4jEntryPoint.java:21)."""

    def __init__(self):
        self._models: dict = {}

    # -- reference: fit(params) with model file + train directories
    def fit(self, model_file_path: str, nb_epoch: int,
            train_features_directory: str, train_labels_directory: str,
            dim_ordering: str = "tf", model_type: str = "sequential") -> dict:
        from deeplearning4j_tpu.modelimport.keras_import import KerasModelImport

        if model_type != "sequential":
            raise ValueError("only sequential models supported (reference "
                             "DeepLearning4jEntryPoint parity)")
        net = KerasModelImport.import_keras_sequential_model_and_weights(
            model_file_path)
        xs = HDF5MiniBatchDataSetIterator(train_features_directory)
        ys = HDF5MiniBatchDataSetIterator(train_labels_directory)
        if len(xs) != len(ys):
            raise ValueError("feature/label batch counts differ")
        for _ in range(int(nb_epoch)):
            for x, y in zip(xs, ys):
                # lint: host-sync-in-hot-loop-ok (staging HDF5 host batches before fit, not a device read)
                net.fit(np.asarray(x, np.float32), np.asarray(y, np.float32))
        self._models[model_file_path] = net
        return {"batches": len(xs), "epochs": int(nb_epoch),
                # lint: host-sync-in-hot-loop-ok (trusted LazyScore sync, once per RPC after fit)
                "score": float(net.score_value)}

    def evaluate(self, model_file_path: str, features_directory: str,
                 labels_directory: str) -> dict:
        net = self._models.get(model_file_path)
        if net is None:
            from deeplearning4j_tpu.modelimport.keras_import import KerasModelImport
            net = KerasModelImport.import_keras_sequential_model_and_weights(
                model_file_path)
            self._models[model_file_path] = net
        xs = HDF5MiniBatchDataSetIterator(features_directory)
        ys = HDF5MiniBatchDataSetIterator(labels_directory)
        correct = total = 0
        for x, y in zip(xs, ys):
            pred = np.argmax(np.asarray(net.output(np.asarray(x, np.float32))),
                             axis=-1)
            correct += int(np.sum(pred == np.argmax(y, axis=-1)))
            total += len(y)
        return {"accuracy": correct / max(total, 1), "examples": total}

    def predict(self, model_file_path: str, features: list) -> dict:
        net = self._models.get(model_file_path)
        if net is None:
            from deeplearning4j_tpu.modelimport.keras_import import KerasModelImport
            net = KerasModelImport.import_keras_sequential_model_and_weights(
                model_file_path)
            self._models[model_file_path] = net
        out = net.output(np.asarray(features, np.float32))
        return {"predictions": np.asarray(out).tolist()}


_RPC_METHODS = frozenset({"fit", "evaluate", "predict"})

_LOOPBACK_HOSTS = frozenset({"127.0.0.1", "::1", "localhost", ""})


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                req = json.loads(line)
                token = self.server.auth_token
                if token is not None:
                    import hmac
                    supplied = str(req.get("token", ""))
                    if not hmac.compare_digest(supplied, token):
                        raise PermissionError("invalid or missing auth token")
                name = req["method"]
                if name not in _RPC_METHODS:
                    raise ValueError(f"unknown method {name!r} "
                                     f"(allowed: {sorted(_RPC_METHODS)})")
                method = getattr(self.server.entry_point, name)
                result = method(**req.get("params", {}))
                resp = {"ok": True, "result": result}
            except Exception as e:  # report, keep serving
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class Server:
    """JSON-lines TCP gateway (reference keras/Server.java:18 py4j
    GatewayServer equivalent). ``start()`` serves on a background thread.

    The RPC surface reads model/dataset files from caller-supplied paths, so
    exposure beyond loopback is gated: binding a non-loopback host requires
    an ``auth_token``, which every request must then carry as ``token``
    (checked with a constant-time compare)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 entry_point: Optional[DeepLearning4jEntryPoint] = None,
                 auth_token: Optional[str] = None):
        if host not in _LOOPBACK_HOSTS and not auth_token:
            raise ValueError(
                f"refusing to bind {host!r}: the gateway executes file-path "
                "RPCs; pass auth_token= to expose it beyond loopback")
        self._srv = socketserver.ThreadingTCPServer(
            (host, port), _Handler, bind_and_activate=True)
        self._srv.daemon_threads = True
        self._srv.entry_point = entry_point or DeepLearning4jEntryPoint()
        self._srv.auth_token = auth_token
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def start(self) -> "Server":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def call(host: str, port: int, method: str, token: Optional[str] = None,
         **params):
    """Convenience client for the gateway protocol."""
    req = {"method": method, "params": params}
    if token is not None:
        req["token"] = token
    with socket.create_connection((host, port)) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    resp = json.loads(buf.decode())
    if not resp.get("ok"):
        raise RuntimeError(resp.get("error", "gateway call failed"))
    return resp["result"]


# ----------------------------------------------------------- serving engine
from deeplearning4j_tpu.keras_server.admission import (  # noqa: E402
    AdmissionController, RejectedError)
from deeplearning4j_tpu.keras_server.registry import (  # noqa: E402
    ModelRegistry, ModelVersion, global_model_registry,
    set_global_model_registry)
from deeplearning4j_tpu.keras_server.batcher import (  # noqa: E402
    MicroBatcher, batch_bucket)
from deeplearning4j_tpu.keras_server.decode import (  # noqa: E402
    DecodeEngine, DecodeSession)
from deeplearning4j_tpu.keras_server.replica import (  # noqa: E402
    Replica, ReplicaSet)
from deeplearning4j_tpu.keras_server.autoscaler import (  # noqa: E402
    Autoscaler)
from deeplearning4j_tpu.keras_server.streaming import (  # noqa: E402
    StreamSessions)
from deeplearning4j_tpu.keras_server.serving import (  # noqa: E402
    InferenceServer, active_server, serve_status)
from deeplearning4j_tpu.keras_server.loadgen import (  # noqa: E402
    run_ab, run_closed_loop, run_decode_ab, run_open_loop,
    run_ramp_ab, run_replica_ab, run_token_stream_load)

__all__ = [
    "HDF5MiniBatchDataSetIterator", "DeepLearning4jEntryPoint", "Server",
    "call",
    "AdmissionController", "RejectedError",
    "ModelRegistry", "ModelVersion", "global_model_registry",
    "set_global_model_registry",
    "MicroBatcher", "batch_bucket", "StreamSessions",
    "DecodeEngine", "DecodeSession",
    "Replica", "ReplicaSet", "Autoscaler",
    "InferenceServer", "active_server", "serve_status",
    "run_ab", "run_closed_loop", "run_decode_ab", "run_open_loop",
    "run_ramp_ab", "run_replica_ab", "run_token_stream_load",
]
