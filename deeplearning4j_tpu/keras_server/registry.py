"""Model registry: versioned serving models with atomic hot-swap.

Reference analog: ``KerasModelEndpoint`` holds ONE imported model per
endpoint and reloads in place. Here a registry maps ``name -> {version ->
ModelVersion}`` where each version pins a compiled **non-donated**
``predict_fn`` (:func:`nn.inference.make_predict_fn`) over a parameter
snapshot, so:

- registering version N+1 builds its predict program OFF the serving path,
  then swaps the active pointer under the lock — in-flight requests that
  already resolved version N complete against N's pinned buffers (zero
  request loss, pinned by tests/test_serving.py);
- a later ``fit()`` on the source network cannot corrupt serving (the
  snapshot is real buffer copies), and serving cannot be corrupted BY
  training donation.

Models load from ``model_serializer`` zips (either network type via
``guess_model``) or Keras HDF5 exports (``KerasModelImport``), or register
directly from an in-memory network.
"""
from __future__ import annotations

import functools
import threading
import zipfile
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.inference import PredictFn, make_predict_fn
from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.tracing import trace_span


def load_model_file(path: str):
    """Parse a serving model file into a network: a ``model_serializer``
    zip (either network type) or a Keras HDF5 export. Shared by
    :meth:`ModelRegistry.load` and ``ReplicaSet.load``."""
    if zipfile.is_zipfile(path):
        from deeplearning4j_tpu.utils.model_serializer import guess_model
        return guess_model(path)
    from deeplearning4j_tpu.modelimport.keras_import import KerasModelImport
    try:
        return KerasModelImport.import_keras_sequential_model_and_weights(path)
    except ValueError:
        return KerasModelImport.import_keras_model_and_weights(path)


def _derive_warmup_example(net):
    """(1, n_in) float32 zeros for plain feedforward stacks; None (skip
    warmup) for recurrent/conv/graph first layers whose input layout can't
    be derived from ``n_in`` alone — callers pass ``warmup_example`` for
    those."""
    if type(net).__name__ == "ComputationGraph":
        return None
    layers = getattr(getattr(net, "conf", None), "layers", None)
    if not layers:
        return None
    first = layers[0]
    if type(first).__module__.rsplit(".", 1)[-1] != "feedforward":
        return None
    n_in = getattr(first, "n_in", None)
    if not n_in:
        return None
    import numpy as np
    return np.zeros((1, int(n_in)), np.float32)


class ModelVersion:
    """One immutable (name, version) serving unit."""

    def __init__(self, name: str, version: str, net, predict_fn: PredictFn,
                 source: str = "memory", quant: str = None):
        self.name = name
        self.version = version
        self.net = net
        self.predict_fn = predict_fn
        self.source = source
        #: serving DtypePolicy this version was pinned under (None = the
        #: network's policy dtype; "int8" = quantized weights at rest)
        self.quant = quant
        #: the streaming seam exists on both network types
        self.streaming_capable = hasattr(net, "rnn_time_step")

    def describe(self) -> dict:
        return {"name": self.name, "version": self.version,
                "source": self.source, "quant": self.quant,
                "sharding": self.predict_fn.sharding,
                "param_bytes": self.predict_fn.param_bytes,
                "streaming_capable": self.streaming_capable,
                "predict_calls": self.predict_fn.calls}


class ModelRegistry:
    """Thread-safe versioned model store with an atomic active pointer.

    ``warmup_max_batch`` opts registration into parallel AOT warmup: every
    power-of-two micro-batch bucket program up to that cap is pre-built
    (thread pool, executable-cache-backed) BEFORE the active pointer moves,
    so a fresh pin or hot swap serves its first real request without an XLA
    stall. Off by default — existing compile-count semantics are pinned by
    tests."""

    def __init__(self, metrics=None, warmup_max_batch: Optional[int] = None,
                 warmup_workers: int = 4):
        self._lock = threading.RLock()
        self._versions: Dict[str, Dict[str, ModelVersion]] = {}
        self._active: Dict[str, str] = {}
        #: target model name -> draft model name (speculative decoding):
        #: the draft is a REGULAR registered model — versioned, hot-
        #: swappable, visible in status() — the link only names it
        self._drafts: Dict[str, str] = {}
        self.warmup_max_batch = warmup_max_batch
        self.warmup_workers = warmup_workers
        self._metrics = metrics or global_registry()
        self._g_models = self._metrics.gauge(
            _n.SERVE_MODELS_LOADED, "model versions held by the registry")
        self._c_swaps = self._metrics.counter(
            _n.SERVE_HOT_SWAPS_TOTAL, "active-version hot swaps")

    # ------------------------------------------------------------- loading
    def register(self, name: str, net, version: Optional[str] = None,
                 source: str = "memory",
                 quant: Optional[str] = None,
                 sharding: Optional[str] = None, mesh=None, device=None,
                 replica: Optional[int] = None,
                 warmup_example=None,
                 draft_for: Optional[str] = None) -> ModelVersion:
        """Pin ``net`` for serving and make it the active version.

        The predict program is built (and its parameter snapshot copied)
        BEFORE the active pointer moves, so the swap itself is a dict
        assignment under the lock — atomic with respect to ``active()``.
        ``quant="int8"`` opts the version into the int8 serving DtypePolicy:
        per-channel scales calibrated at pin time, int8 weights at rest for
        both the predict program and this version's decode engines.
        ``sharding``/``mesh``/``device``/``replica`` choose the pin
        placement (see :class:`nn.inference.PredictFn`) — the ReplicaSet
        passes its per-replica mesh or device through here.
        ``draft_for`` additionally links this model as the speculative-
        decode draft of the named target model (see :meth:`link_draft`).
        """
        with self._lock:
            version = version or f"v{len(self._versions.get(name, {})) + 1}"
            if version in self._versions.get(name, {}):
                raise ValueError(
                    f"model {name!r} already has version {version!r}; "
                    "versions are immutable — register a new one")
        with trace_span("registry.register", model=name, version=version,
                        warmup=bool(self.warmup_max_batch)) as rsp:
            pf = make_predict_fn(net, version=version, quant=quant,
                                 sharding=sharding, mesh=mesh, device=device,
                                 replica=replica)
            if self.warmup_max_batch:
                # still off the serving path: the old version keeps serving
                # while every bucket program of the new one is built
                self._warmup(pf, net, warmup_example)
            with self._lock:
                swapping = name in self._active
                mv = ModelVersion(name, version, net, pf, source=source,
                                  quant=pf.quant)
                self._versions.setdefault(name, {})[version] = mv
                self._active[name] = version
                self._g_models.set(
                    sum(len(v) for v in self._versions.values()))
                if swapping:
                    self._c_swaps.labels(model=name).inc()
            rsp.set_attr(hot_swap=swapping)
        if draft_for is not None:
            self.link_draft(draft_for, name)
        return mv

    # ------------------------------------------------- speculative drafts
    def link_draft(self, name: str, draft_name: str) -> None:
        """Name ``draft_name`` as the speculative-decode draft model of
        ``name``. The draft is an ordinary registered model (its active
        version resolves per-decode-engine, so hot-swapping the draft
        retires engines exactly like hot-swapping the target)."""
        with self._lock:
            if draft_name not in self._versions:
                raise KeyError(
                    f"draft model {draft_name!r} is not registered "
                    f"(loaded: {sorted(self._versions)})")
            if draft_name == name:
                raise ValueError(
                    f"model {name!r} cannot be its own spec-decode draft")
            self._drafts[name] = draft_name

    def draft_of(self, name: str) -> Optional[str]:
        """The linked draft model name for ``name``, or None."""
        with self._lock:
            return self._drafts.get(name)

    # ------------------------------------------------------------- warmup
    @staticmethod
    def warmup_buckets(max_batch: int) -> List[int]:
        """The micro-batcher's bucket ladder: powers of two capped at
        ``max_batch`` (log2(max_batch)+1 entries when it is a power of
        two) — exactly the programs live traffic would compile lazily."""
        buckets, b = [], 1
        while b < max_batch:
            buckets.append(b)
            b *= 2
        buckets.append(max_batch)
        return buckets

    def _warmup(self, pf: PredictFn, net, example=None) -> None:
        """Pre-build every bucket program for a fresh pin concurrently.
        ``example`` is one input row batch (array, or tuple of arrays for
        graphs); when omitted it is derived from the config's first layer.
        Warmup is best-effort: an underivable input shape skips it."""
        import numpy as np

        from deeplearning4j_tpu.nn import compile_cache

        if example is None:
            example = _derive_warmup_example(net)
            if example is None:
                return
        examples = [np.asarray(e) for e in
                    (example if isinstance(example, (tuple, list))
                     else (example,))]

        def one(b):
            pf.warm(*[np.zeros((b,) + tuple(e.shape[1:]), e.dtype)
                      for e in examples])

        compile_cache.warm_parallel(
            [functools.partial(one, b)
             for b in self.warmup_buckets(self.warmup_max_batch)],
            site="registry", workers=self.warmup_workers)

    def load(self, name: str, path: str, version: Optional[str] = None,
             quant: Optional[str] = None) -> ModelVersion:
        """Load a model file and register it: a ``model_serializer`` zip
        (either network type) or a Keras HDF5 export."""
        return self.register(name, load_model_file(path), version=version,
                             source=path, quant=quant)

    # ------------------------------------------------------------- lookup
    def active(self, name: str) -> ModelVersion:
        with self._lock:
            try:
                return self._versions[name][self._active[name]]
            except KeyError:
                raise KeyError(
                    f"no model {name!r} in registry "
                    f"(loaded: {sorted(self._versions)})") from None

    def get(self, name: str, version: str) -> ModelVersion:
        with self._lock:
            return self._versions[name][version]

    def set_active(self, name: str, version: str) -> ModelVersion:
        """Point ``name`` at an already-registered version (rollback)."""
        with self._lock:
            mv = self._versions[name][version]  # KeyError = no such version
            if self._active[name] != version:
                self._active[name] = version
                self._c_swaps.labels(model=name).inc()
            return mv

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._versions)

    def status(self) -> dict:
        """The /serve/status registry half (the batcher adds queue stats)."""
        with self._lock:
            return {
                "models": {
                    name: {
                        "active": self._active[name],
                        "versions": {
                            v: mv.describe()
                            for v, mv in sorted(versions.items())},
                    }
                    for name, versions in sorted(self._versions.items())},
                "drafts": dict(sorted(self._drafts.items())),
            }


_GLOBAL: Optional[ModelRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def global_model_registry() -> ModelRegistry:
    """THE registry the UI's /serve/status route reads."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ModelRegistry()
        return _GLOBAL


def set_global_model_registry(
        registry: Optional[ModelRegistry]) -> Optional[ModelRegistry]:
    """Swap the global registry (tests); returns the previous one."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, registry
        return prev
