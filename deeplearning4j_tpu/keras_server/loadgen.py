"""Load generation for the serving engine: open/closed loop over HTTP.

The open-loop client is the honest one for capacity questions: requests
are scheduled on a fixed clock (``offered_qps``) regardless of how fast
the server answers, so saturation shows up as growing latency and 429s
instead of the client politely slowing down (closed-loop coordinated
omission). Latency is measured from the request's SCHEDULED time, so
client-side lag counts against the server the way a real user would
experience it. The closed-loop client (N workers, back-to-back) measures
best-case per-stream latency and peak sustainable throughput.

``run_ab`` is the headline harness: the same model served unbatched
(``max_batch=1``) vs micro-batched at the same offered QPS, one JSONL
record with p50/p99, achieved QPS, batch occupancy, and the compile-
tracker recompile count — the acceptance check is
``recompiles == bucket count`` (steady state never recompiles).

By default ``run_ab`` runs the load client **in a separate process**
(``python -m deeplearning4j_tpu.keras_server.loadgen``): client, HTTP
handlers, and the dispatcher otherwise contend for ONE interpreter lock,
which caps both phases at the same combined-GIL ceiling and masks the
batching win the A/B exists to measure. Client startup (process spawn +
imports) happens before the client schedules its first request, so it
never lands on the measurement clock.

``run_token_stream_load``/``run_decode_ab`` are the decode-path analogue:
an in-process open-loop TOKEN-streaming client against a
:class:`~deeplearning4j_tpu.keras_server.decode.DecodeEngine`. Sessions
are offered at a fixed sessions/sec clock; per-token host timestamps give
TTFT (from the SCHEDULED arrival, same no-coordinated-omission rule) and
inter-token latency percentiles. The A/B pits iteration-level continuous
batching against request-level static batching at equal offered rate, and
int8 weight-only decode against dense — same seeded session mix, fresh
clone per phase so ``recompiles == bucket count`` holds per phase.
"""
from __future__ import annotations

import http.client
import json
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[i]


def _serve_compile_count() -> int:
    from deeplearning4j_tpu.nn.inference import PREDICT_PROGRAM_NAME
    from deeplearning4j_tpu.observability.compile_tracker import \
        global_tracker
    return sum(1 for e in global_tracker().snapshot_events()
               if PREDICT_PROGRAM_NAME in e.get("fn", ""))


class _Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies_ms: List[float] = []
        self.ok = 0
        self.rejected = 0
        self.errors = 0

    def record(self, status: int, latency_ms: float) -> None:
        with self.lock:
            if status == 200:
                self.ok += 1
                self.latencies_ms.append(latency_ms)
            elif status == 429:
                self.rejected += 1
            else:
                self.errors += 1

    def summary(self) -> dict:
        with self.lock:
            lat = sorted(self.latencies_ms)
            return {"ok": self.ok, "rejected": self.rejected,
                    "errors": self.errors,
                    "p50_ms": round(percentile(lat, 0.50), 3),
                    "p90_ms": round(percentile(lat, 0.90), 3),
                    "p99_ms": round(percentile(lat, 0.99), 3)}


def _connect(host: str, port: int,
             timeout: float = 30.0) -> http.client.HTTPConnection:
    """Persistent connection with Nagle off — mirrors the server side; a
    buffered small-segment request otherwise hits the 40ms delayed-ACK
    stall and the load test measures the kernel timer, not the server."""
    import socket
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.connect()
    conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return conn


#: trace-id RNG: an instance, not the hidden global — ids must be unique,
#: never reproducible, and must not perturb seeded workload generation
_trace_rand = random.Random()


def _mint_traceparent() -> str:
    """A fresh client-side W3C trace context per request, so server-side
    span trees parent under the load client's ids (exactly what a fronting
    gateway would send) and a slow request is findable by the id the
    response echoes back."""
    return (f"00-{_trace_rand.getrandbits(128):032x}"
            f"-{_trace_rand.getrandbits(64):016x}-01")


def _post_predict(conn: http.client.HTTPConnection, model: str,
                  payload: bytes) -> int:
    conn.request("POST", "/v1/predict", body=payload,
                 headers={"Content-Type": "application/json",
                          "traceparent": _mint_traceparent()})
    resp = conn.getresponse()
    resp.read()
    return resp.status


def _worker_bodies(model: str, example) -> Callable[[int], bytes]:
    if callable(example):
        return lambda i: json.dumps(
            {"model": model, "inputs": np.asarray(example(i)).tolist()}
        ).encode()
    body = json.dumps(
        {"model": model, "inputs": np.asarray(example).tolist()}).encode()
    return lambda i: body


def run_open_loop(port: int, model: str, example, *, qps: float,
                  duration_s: float, workers: int = 32,
                  host: str = "127.0.0.1") -> dict:
    """Fixed-rate load: request i fires at ``t0 + i/qps``; a late worker
    pool never thins the offered schedule (requests queue client-side and
    the latency clock keeps running from the scheduled instant)."""
    n_total = max(1, int(qps * duration_s))
    make_body = _worker_bodies(model, example)
    stats = _Stats()
    counter = {"i": 0}
    counter_lock = threading.Lock()
    t0 = time.perf_counter() + 0.05  # let workers reach their first wait

    def work():
        conn = _connect(host, port)
        while True:
            with counter_lock:
                i = counter["i"]
                if i >= n_total:
                    break
                counter["i"] = i + 1
            t_sched = t0 + i / qps
            delay = t_sched - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                status = _post_predict(conn, model, make_body(i))
            except OSError:
                conn.close()
                conn = _connect(host, port)
                stats.record(-1, 0.0)
                continue
            stats.record(status,
                         (time.perf_counter() - t_sched) * 1e3)
        conn.close()

    threads = [threading.Thread(target=work, daemon=True)
               for _ in range(max(1, min(workers, n_total)))]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.perf_counter() - t_start, 1e-9)
    out = stats.summary()
    out.update({"mode": "open", "offered_qps": round(qps, 3),
                "achieved_qps": round(out["ok"] / wall, 3),
                "duration_s": round(wall, 3), "requests": n_total})
    return out


def run_closed_loop(port: int, model: str, example, *, workers: int,
                    requests_per_worker: int,
                    host: str = "127.0.0.1") -> dict:
    """N concurrent streams, back-to-back requests: peak throughput."""
    make_body = _worker_bodies(model, example)
    stats = _Stats()

    def work(wid: int):
        conn = _connect(host, port)
        for j in range(requests_per_worker):
            t_send = time.perf_counter()
            try:
                status = _post_predict(
                    conn, model, make_body(wid * requests_per_worker + j))
            except OSError:
                conn.close()
                conn = _connect(host, port)
                stats.record(-1, 0.0)
                continue
            stats.record(status, (time.perf_counter() - t_send) * 1e3)
        conn.close()

    threads = [threading.Thread(target=work, args=(w,), daemon=True)
               for w in range(workers)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.perf_counter() - t_start, 1e-9)
    out = stats.summary()
    out.update({"mode": "closed", "workers": workers,
                "achieved_qps": round(out["ok"] / wall, 3),
                "duration_s": round(wall, 3),
                "requests": workers * requests_per_worker})
    return out


def _client_cmd(port: int, model: str, shape, *, extra: List[str]) -> list:
    import sys
    return [sys.executable, "-m", "deeplearning4j_tpu.keras_server.loadgen",
            "--port", str(port), "--model", model,
            "--shape", ",".join(str(int(s)) for s in shape)] + extra


def _run_client(cmd: list, timeout_s: float) -> dict:
    """Launch the load client in its own process and parse its JSON line."""
    import subprocess
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          timeout=timeout_s)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(rec, dict) and "achieved_qps" in rec:
            return rec
    raise RuntimeError(
        f"load client produced no record (rc={proc.returncode}): "
        + (proc.stderr or "")[-400:])


def run_open_loop_proc(port: int, model: str, shape, *, qps: float,
                       duration_s: float, workers: int = 32) -> dict:
    """run_open_loop in a separate process (own GIL); the client
    regenerates its payload from ``shape`` (load shape matters, values
    don't)."""
    return _run_client(
        _client_cmd(port, model, shape, extra=[
            "--qps", str(qps), "--duration", str(duration_s),
            "--workers", str(workers)]),
        timeout_s=duration_s * 20 + 120)


def run_closed_loop_proc(port: int, model: str, shape, *, workers: int,
                         requests_per_worker: int) -> dict:
    return _run_client(
        _client_cmd(port, model, shape, extra=[
            "--closed", "--workers", str(workers),
            "--requests", str(requests_per_worker)]),
        timeout_s=600)


def run_ab(net, *, model: str = "model", qps: float = 200.0,
           duration_s: float = 3.0, max_batch: int = 32,
           max_latency_s: float = 0.004, max_queue: int = 512,
           example=None, workers: int = 32,
           warmup_requests: int = 8, isolate_client: bool = True,
           record_path: Optional[str] = None) -> dict:
    """Serve ``net`` unbatched then micro-batched at the SAME offered QPS;
    return (and optionally append as JSONL) the A/B record.
    ``isolate_client=False`` keeps the load client in-process (faster to
    start, but client GIL contention depresses both phases)."""
    from .registry import ModelRegistry
    from .serving import InferenceServer
    if example is None:
        raise ValueError("pass example= (one input row, shape [1, ...])")
    example = np.asarray(example)
    phases = {}
    for phase, batch in (("unbatched", 1), ("batched", max_batch)):
        registry = ModelRegistry()
        # a fresh clone per phase = a fresh compile cache, so each phase's
        # recompile count is exactly ITS bucket set (the acceptance pin
        # `recompiles == bucket count` must not see the other phase's warmup)
        registry.register(model, net.clone(), version="v1")
        compiles_before = _serve_compile_count()
        server = InferenceServer(
            registry, max_batch=batch,
            max_latency_s=(0.0 if batch == 1 else max_latency_s),
            max_queue=max_queue).start()
        try:
            # warm the compile cache off the clock (steady-state contract)
            run_closed_loop(server.port, model, example, workers=1,
                            requests_per_worker=warmup_requests)
            if isolate_client:
                res = run_open_loop_proc(
                    server.port, model, example.shape, qps=qps,
                    duration_s=duration_s, workers=workers)
            else:
                res = run_open_loop(server.port, model, example, qps=qps,
                                    duration_s=duration_s, workers=workers)
            bstats = server.batcher.stats()
        finally:
            server.stop()
        res["batch_occupancy"] = round(bstats["mean_occupancy"], 4)
        res["bucket_count"] = bstats["bucket_count"]
        res["dispatches"] = bstats["dispatches"]
        res["recompiles"] = _serve_compile_count() - compiles_before
        res["max_batch"] = batch
        phases[phase] = res
    rec = {
        "harness": "keras_server.loadgen.run_ab",
        "model": model, "offered_qps": qps, "duration_s": duration_s,
        "max_batch": max_batch, "max_latency_s": max_latency_s,
        "unbatched": phases["unbatched"], "batched": phases["batched"],
        "batched_speedup": round(
            phases["batched"]["achieved_qps"]
            / max(phases["unbatched"]["achieved_qps"], 1e-9), 3),
        "p99_improvement": round(
            phases["unbatched"]["p99_ms"]
            / max(phases["batched"]["p99_ms"], 1e-9), 3),
    }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def _replica_compile_counts(n_replicas: int) -> List[int]:
    """Per-replica serve-predict compile counts: every ReplicaSet member's
    program name ends in ``~r<i>`` (nn/inference.make_predict_fn), which is
    what makes `recompiles == buckets` checkable PER replica."""
    from deeplearning4j_tpu.nn.inference import PREDICT_PROGRAM_NAME
    from deeplearning4j_tpu.observability.compile_tracker import \
        global_tracker
    counts = [0] * n_replicas
    for e in global_tracker().snapshot_events():
        fn = e.get("fn", "")
        if PREDICT_PROGRAM_NAME not in fn:
            continue
        for i in range(n_replicas):
            if fn.endswith(f"~r{i}"):
                counts[i] += 1
                break
    return counts


def run_replica_ab(net, *, model: str = "model", replicas: int = 2,
                   sharding: Optional[str] = None, qps: float = 200.0,
                   duration_s: float = 3.0, max_batch: int = 32,
                   max_latency_s: float = 0.004, max_queue: int = 512,
                   example=None, workers: int = 32,
                   warmup_requests: int = 8, isolate_client: bool = True,
                   record_path: Optional[str] = None) -> dict:
    """QPS-vs-replicas scaling A/B: 1 replica vs ``replicas`` behind the
    least-queue router, at the SAME offered QPS (pick one that saturates
    the single replica, so the scaled phase shows real headroom).
    ``sharding`` routes every replica's pin through the partition-rule
    engine on its own mesh slice. The scaled phase reports per-replica
    recompiles vs bucket counts (the compile-cache contract holds per
    replica because each pin is its own ``~r<i>`` program)."""
    from .registry import ModelRegistry
    from .serving import InferenceServer
    if example is None:
        raise ValueError("pass example= (one input row, shape [1, ...])")
    example = np.asarray(example)
    phases = {}
    for phase, n in (("baseline", 1), ("scaled", max(replicas, 1))):
        compiles_before = _serve_compile_count()
        counts_before = _replica_compile_counts(max(replicas, 1))
        # a fresh clone per phase = a fresh compile cache per phase (same
        # contract as run_ab); the baseline stays on the classic single-
        # batcher path unless sharding forces replica mode
        if n > 1 or sharding is not None:
            server = InferenceServer(
                replicas=n, sharding=sharding, max_batch=max_batch,
                max_latency_s=max_latency_s, max_queue=max_queue)
            server.register(model, net.clone(), version="v1")
        else:
            registry = ModelRegistry()
            registry.register(model, net.clone(), version="v1")
            server = InferenceServer(
                registry, max_batch=max_batch, max_latency_s=max_latency_s,
                max_queue=max_queue)
        server.start()
        try:
            # warm every replica's compile cache off the clock: concurrent
            # closed-loop workers spread over the router
            run_closed_loop(server.port, model, example,
                            workers=max(2, 2 * n),
                            requests_per_worker=warmup_requests)
            if isolate_client:
                res = run_open_loop_proc(
                    server.port, model, example.shape, qps=qps,
                    duration_s=duration_s, workers=workers)
            else:
                res = run_open_loop(server.port, model, example, qps=qps,
                                    duration_s=duration_s, workers=workers)
            if server.replica_set is not None:
                qstats = server.replica_set.queue_stats()
                rstats = server.replica_set.stats()["replicas"]
            else:
                qstats = server.batcher.stats()
                rstats = None
        finally:
            server.stop()
        res["batch_occupancy"] = round(qstats["mean_occupancy"], 4)
        res["bucket_count"] = qstats["bucket_count"]
        res["dispatches"] = qstats["dispatches"]
        res["recompiles"] = _serve_compile_count() - compiles_before
        res["replicas"] = n
        if rstats is not None:
            counts_after = _replica_compile_counts(n)
            res["per_replica"] = [
                {"replica": r["replica"], "routed": r["routed"],
                 "dispatches": r["dispatches"],
                 "bucket_count": r["bucket_count"],
                 "recompiles": counts_after[i] - counts_before[i],
                 "recompiles_match_buckets":
                     counts_after[i] - counts_before[i]
                     == r["bucket_count"]}
                for i, r in enumerate(rstats)]
        phases[phase] = res
    rec = {
        "harness": "keras_server.loadgen.run_replica_ab",
        "model": model, "offered_qps": qps, "duration_s": duration_s,
        "max_batch": max_batch, "replicas": replicas,
        "sharding": sharding or "none",
        "replicas_1": phases["baseline"], "replicas_n": phases["scaled"],
        "replica_speedup": round(
            phases["scaled"]["achieved_qps"]
            / max(phases["baseline"]["achieved_qps"], 1e-9), 3),
        "recompiles_match_buckets": all(
            p["recompiles_match_buckets"]
            for p in phases["scaled"].get("per_replica", [])),
    }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def _run_ramp_phase(port: int, model: str, example, *,
                    segments, workers: int = 16,
                    host: str = "127.0.0.1") -> List[tuple]:
    """Open-loop ramp client: ``segments`` is a sequence of
    ``(qps, seconds)`` steps played back to back. Send times are fixed by
    the schedule (latency measured from the SCHEDULED instant — no
    coordinated omission, same contract as :func:`run_open_loop`).
    Returns per-request ``(t_sched_s, status, latency_ms)`` samples."""
    bodies = _worker_bodies(model, example)
    offsets: List[float] = []
    t = 0.0
    for qps, seg_s in segments:
        n = max(1, int(qps * seg_s))
        offsets.extend(t + i / qps for i in range(n))
        t += seg_s
    samples: List[tuple] = []
    lock = threading.Lock()
    next_i = [0]
    t0 = time.perf_counter()

    def worker():
        conn = _connect(host, port)
        try:
            while True:
                with lock:
                    i = next_i[0]
                    if i >= len(offsets):
                        return
                    next_i[0] += 1
                sched = offsets[i]
                delay = t0 + sched - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    status = _post_predict(conn, model, bodies(i))
                except Exception:
                    status = -1
                    conn.close()
                    conn = _connect(host, port)
                lat_ms = (time.perf_counter() - (t0 + sched)) * 1e3
                with lock:
                    samples.append((sched, status, lat_ms))
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return samples


def _ramp_summary(samples: List[tuple], slo_ms: float) -> dict:
    """Fold ramp samples into SLO-violation-seconds: a wall-clock second
    is in violation when its p99 exceeds ``slo_ms`` or any request in it
    was rejected or errored. ``lost`` counts admitted-but-failed requests
    (a 429 is an explicit reject, not a loss)."""
    by_second: Dict[int, List[tuple]] = {}
    for sched, status, lat_ms in samples:
        by_second.setdefault(int(sched), []).append((status, lat_ms))
    violation_s = 0
    for sec in sorted(by_second):
        rows = by_second[sec]
        lat = sorted(l for s, l in rows if s == 200)
        bad = any(s != 200 for s, _ in rows)
        if bad or (lat and percentile(lat, 0.99) > slo_ms):
            violation_s += 1
    lat_all = sorted(l for _, s, l in samples if s == 200)
    return {
        "requests": len(samples),
        "ok": sum(1 for _, s, _ in samples if s == 200),
        "rejected": sum(1 for _, s, _ in samples if s == 429),
        "lost": sum(1 for _, s, _ in samples if s not in (200, 429)),
        "p50_ms": round(percentile(lat_all, 0.50), 3),
        "p99_ms": round(percentile(lat_all, 0.99), 3),
        "slo_violation_seconds": violation_s,
    }


def run_ramp_ab(net, *, model: str = "model", qps_low: float = 20.0,
                qps_high: Optional[float] = None, segment_s: float = 2.0,
                slo_ms: float = 250.0, min_replicas: int = 1,
                max_replicas: int = 4, cooldown_s: float = 1.0,
                interval_s: float = 0.2, max_batch: int = 32,
                max_latency_s: float = 0.004, max_queue: int = 64,
                example=None, workers: int = 16,
                warmup_requests: int = 8,
                record_path: Optional[str] = None) -> dict:
    """The autoscaling headline A/B: an open-loop ramp (low → high → low,
    default 10x swing) against (a) an autoscaled fleet and (b) a static
    fleet sized to the autoscaled run's time-weighted AVERAGE replica
    count — same average hardware, different placement in time. The
    record carries ``slo_violation_seconds_auto/static`` (the acceptance
    floor: auto strictly below static), ``lost_requests`` (must be zero:
    scale-in drains without loss) and ``scale_out_latency_s`` (decision →
    routable, warm-path bounded)."""
    from .registry import ModelRegistry
    from .serving import InferenceServer
    if example is None:
        raise ValueError("pass example= (one input row, shape [1, ...])")
    example = np.asarray(example)
    qps_high = qps_high if qps_high is not None else 10.0 * qps_low
    segments = ((qps_low, segment_s), (qps_high, segment_s),
                (qps_low, segment_s))

    # ---- phase 1: autoscaled fleet, fleet-size sampler alongside
    server = InferenceServer(
        replicas=min_replicas, autoscale=True, min_replicas=min_replicas,
        max_replicas=max_replicas, autoscale_cooldown_s=cooldown_s,
        autoscale_interval_s=interval_s, max_batch=max_batch,
        max_latency_s=max_latency_s, max_queue=max_queue, warmup=True)
    server.register(model, net.clone(), version="v1")
    fleet_samples: List[tuple] = []
    stop = threading.Event()

    def sampler():
        while not stop.wait(0.05):
            fleet_samples.append(
                (time.perf_counter(), server.replica_set.n_replicas))

    server.start()
    sth = threading.Thread(target=sampler, daemon=True)
    sth.start()
    try:
        run_closed_loop(server.port, model, example, workers=2,
                        requests_per_worker=warmup_requests)
        auto_samples = _run_ramp_phase(
            server.port, model, example, segments=segments,
            workers=workers)
        scaler = server.autoscaler.status()
    finally:
        stop.set()
        sth.join(2.0)
        server.stop()
    auto = _ramp_summary(auto_samples, slo_ms)
    if len(fleet_samples) > 1:
        weighted = sum(
            n * (fleet_samples[i + 1][0] - fleet_samples[i][0])
            for i, (_, n) in enumerate(fleet_samples[:-1]))
        span = fleet_samples[-1][0] - fleet_samples[0][0]
        avg_replicas = weighted / span if span > 0 else float(min_replicas)
    else:
        avg_replicas = float(min_replicas)

    # ---- phase 2: static fleet at the SAME average replica count
    static_n = max(1, round(avg_replicas))
    if static_n > 1:
        server = InferenceServer(
            replicas=static_n, max_batch=max_batch,
            max_latency_s=max_latency_s, max_queue=max_queue, warmup=True)
        server.register(model, net.clone(), version="v1")
    else:
        registry = ModelRegistry()
        registry.register(model, net.clone(), version="v1")
        server = InferenceServer(
            registry, max_batch=max_batch, max_latency_s=max_latency_s,
            max_queue=max_queue)
    server.start()
    try:
        run_closed_loop(server.port, model, example, workers=2,
                        requests_per_worker=warmup_requests)
        static_samples = _run_ramp_phase(
            server.port, model, example, segments=segments,
            workers=workers)
    finally:
        server.stop()
    static = _ramp_summary(static_samples, slo_ms)

    rec = {
        "harness": "keras_server.loadgen.run_ramp_ab",
        "model": model, "qps_low": qps_low, "qps_high": qps_high,
        "segment_s": segment_s, "slo_ms": slo_ms,
        "min_replicas": min_replicas, "max_replicas": max_replicas,
        "avg_replicas_auto": round(avg_replicas, 3),
        "static_replicas": static_n,
        "auto": auto, "static": static,
        "slo_violation_seconds_auto": auto["slo_violation_seconds"],
        "slo_violation_seconds_static": static["slo_violation_seconds"],
        "lost_requests": auto["lost"],
        "scale_out_latency_s": scaler.get("last_scale_out_latency_s"),
        "scale_events": len(scaler.get("events", [])),
        "auto_beats_static": (auto["slo_violation_seconds"]
                              < static["slo_violation_seconds"]),
    }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


# ----------------------------------------------------- token-streaming load
def _decode_compile_count() -> int:
    from .decode import DECODE_PROGRAM_NAME
    from deeplearning4j_tpu.observability.compile_tracker import \
        global_tracker
    return sum(1 for e in global_tracker().snapshot_events()
               if DECODE_PROGRAM_NAME in e.get("fn", ""))


def _decode_workload(n_sessions: int, vocab: int, prompt_len: int,
                     max_new_tokens: int, seed: int):
    """One deterministic session mix shared by every A/B phase.

    Budgets are LONG-TAILED (3/4 short, 1/4 near the ceiling) because
    that is what decode traffic looks like and it is exactly what
    request-level batching is bad at: one near-ceiling session holds the
    whole batch hostage while the short ones sit drained in their slots.
    """
    rng = np.random.default_rng(seed)
    prompts = [list(map(int, rng.integers(0, vocab,
                                          size=int(rng.integers(1, prompt_len + 1)))))
               for _ in range(n_sessions)]
    short_hi = max(max_new_tokens // 3, 3)
    budgets = [int(rng.integers(max_new_tokens // 2, max_new_tokens + 1))
               if rng.random() < 0.25 else int(rng.integers(2, short_hi))
               for _ in range(n_sessions)]
    return prompts, budgets


def run_token_stream_load(engine, prompts, budgets, *,
                          offered_sps: float,
                          timeout_s: float = 600.0) -> dict:
    """Open-loop token-streaming load against a :class:`DecodeEngine`.

    Session ``i`` is OFFERED at ``t0 + i/offered_sps`` regardless of how
    fast the engine drains — a saturated engine shows up as growing TTFT,
    never as a politely-thinning arrival schedule (no coordinated
    omission: TTFT is measured from the scheduled arrival, which
    ``submit(t_sched=...)`` pins). Per-token host timestamps give the
    inter-token latency distribution; tokens/sec is counted over the wall
    clock from first offer to last completion.
    """
    t0, sessions = _offer_sessions(engine, prompts, budgets, offered_sps)
    for s in sessions:
        s.result(timeout=timeout_s)
    res = _summarize_sessions(sessions, t0)
    res["offered_sps"] = round(offered_sps, 3)
    return res


def _offer_sessions(engine, prompts, budgets, offered_sps: float):
    """Submit the whole mix on the open-loop clock; returns (t0, sessions)."""
    t0 = time.perf_counter() + 0.02
    sessions = []
    for i, (p, b) in enumerate(zip(prompts, budgets)):
        t_sched = t0 + i / offered_sps
        delay = t_sched - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        sessions.append(engine.submit(p, b, t_sched=t_sched))
    return t0, sessions


def run_decode_ab(net, *, model: str = "decode", slots: int = 8,
                  n_sessions: int = 48, prompt_len: int = 4,
                  max_new_tokens: int = 24, offered_sps: Optional[float] = None,
                  eos_id: Optional[int] = None, max_context: int = 128,
                  quant_ab: bool = True, seed: int = 0,
                  record_path: Optional[str] = None) -> dict:
    """Continuous vs static (request-level) decode at EQUAL offered
    sessions/sec, plus an int8-vs-dense accuracy/throughput A/B.

    Every phase runs the identical deterministic session mix on a fresh
    ``net.clone()`` (fresh compile cache, so ``recompiles == bucket
    count`` holds per phase) at the same slot capacity. With
    ``offered_sps=None`` the rate is calibrated to saturate: 1.5x the
    continuous engine's drained session rate from a burst probe — the
    regime where slot occupancy, not arrival, is the binding constraint.
    The headline ratio is tokens/sec; TTFT p99 must not regress.
    """
    from .decode import DecodeEngine
    prompts, budgets = _decode_workload(
        n_sessions, _decode_vocab(net), prompt_len, max_new_tokens, seed)

    if offered_sps is None:
        probe = DecodeEngine(net.clone(), min_slots=slots, max_slots=slots,
                             eos_id=eos_id, max_context=max_context)
        try:
            _decode_warmup(probe)
            n_probe = min(2 * slots, n_sessions)
            res = run_token_stream_load(
                probe, prompts[:n_probe], budgets[:n_probe],
                offered_sps=1e6)  # burst: measure drain rate, not arrival
        finally:
            probe.close()
        offered_sps = max(1.5 * res["achieved_sps"], 1.0)

    def phase(mode: str, quant=None, capture=False) -> Tuple[dict, list]:
        before = _decode_compile_count()
        eng = DecodeEngine(net.clone(), min_slots=slots, max_slots=slots,
                           mode=mode, quant=quant, eos_id=eos_id,
                           max_context=max_context, capture_probs=capture)
        try:
            _decode_warmup(eng)  # bucket compile happens off the clock
            t0, sessions = _offer_sessions(eng, prompts, budgets, offered_sps)
            for s in sessions:
                s.result(timeout=600.0)
            res = _summarize_sessions(sessions, t0)
            st = eng.stats()
        finally:
            eng.close()
        res.update({
            "mode": mode, "quant": quant,
            "offered_sps": round(offered_sps, 3),
            "mean_occupancy": round(st["mean_occupancy"], 4),
            "bucket_count": st["bucket_count"],
            "steps": st["steps"],
            "recompiles": _decode_compile_count() - before,
            "param_bytes": st["param_bytes"],
        })
        return res, sessions

    cont, cont_sessions = phase("continuous", capture=quant_ab)
    stat, _ = phase("static")
    rec = {
        "harness": "keras_server.loadgen.run_decode_ab",
        "model": model, "slots": slots, "n_sessions": n_sessions,
        "offered_sps": round(offered_sps, 3),
        "max_new_tokens": max_new_tokens, "prompt_len": prompt_len,
        "continuous": cont, "static": stat,
        "tokens_per_sec_ratio": round(
            cont["tokens_per_sec"] / max(stat["tokens_per_sec"], 1e-9), 3),
        "ttft_p99_ratio": round(
            stat["ttft_p99_ms"] / max(cont["ttft_p99_ms"], 1e-9), 3),
    }
    if quant_ab:
        q, q_sessions = phase("continuous", quant="int8", capture=True)
        drifts, agree = [], []
        for qs, ds in zip(q_sessions, cont_sessions):
            n = min(len(qs.probs), len(ds.probs))
            if not n:
                continue
            qp = np.stack(qs.probs[:n])
            dp = np.stack(ds.probs[:n])
            drifts.append(float(np.mean(np.abs(qp - dp))))
            agree.append(float(np.mean(
                qp.argmax(-1) == dp.argmax(-1))))
        rec["int8"] = q
        rec["int8_vs_dense"] = {
            "mean_prob_drift": round(float(np.mean(drifts)), 6),
            "top1_agreement": round(float(np.mean(agree)), 4),
            "tokens_per_sec_ratio": round(
                q["tokens_per_sec"] / max(cont["tokens_per_sec"], 1e-9), 3),
            "param_bytes_ratio": round(
                cont["param_bytes"] / max(q["param_bytes"], 1), 3),
        }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def run_paged_ab(net, *, model: str = "decode_paged",
                 dense_slots: int = 4, max_context: int = 128,
                 page_size: int = 16, n_sessions: int = 32,
                 prompt_len: int = 4, max_new_tokens: int = 24,
                 eos_id: Optional[int] = None, seed: int = 0,
                 record_path: Optional[str] = None) -> dict:
    """Dense vs paged KV decode at EQUAL device state bytes.

    The dense engine is pinned at ``dense_slots`` (its HBM ceiling:
    ``slots x max_context`` KV rows whether written or not). The paged
    engine gets a pool of ``dense_slots * max_context / page_size - 1``
    pages — exactly the dense engine's KV bytes including the trash page —
    but ``2 x dense_slots`` slot capacity, so the A/B measures how many
    MORE concurrent sessions the same bytes admit when slots only consume
    pages for tokens they have written. Token streams must be bitwise
    identical (the dense program is the oracle); the headline fields are
    ``sessions_ratio`` (peak concurrent paged / dense capacity) and the
    state-bytes pair that proves the comparison was fair.
    """
    from .decode import DecodeEngine
    prompts, budgets = _decode_workload(
        n_sessions, _decode_vocab(net), prompt_len, max_new_tokens, seed)
    n_pages = dense_slots * (max_context // page_size) - 1

    def phase(kv: str, slots: int, n_pages=None) -> Tuple[dict, list, int]:
        eng = DecodeEngine(net.clone(), min_slots=slots, max_slots=slots,
                           eos_id=eos_id, max_context=max_context,
                           kv=kv, page_size=page_size, n_pages=n_pages)
        try:
            _decode_warmup(eng)
            t0, sessions = _offer_sessions(eng, prompts, budgets, 1e6)
            for s in sessions:
                s.result(timeout=600.0)
            res = _summarize_sessions(sessions, t0)
            st = eng.stats()
            bytes_ = eng.state_bytes()
        finally:
            eng.close()
        res.update({
            "kv": kv, "slots": slots,
            "state_bytes": bytes_,
            "peak_active": st["peak_active"],
            "mean_occupancy": round(st["mean_occupancy"], 4),
        })
        if kv == "paged":
            res.update({
                "pool_pages": st["pool_pages"],
                "prefix_share_ratio": round(st["prefix_share_ratio"], 4),
            })
        return res, sessions, bytes_

    dense, dsess, dbytes = phase("dense", dense_slots)
    paged, psess, pbytes = phase("paged", 2 * dense_slots, n_pages=n_pages)
    bitwise = all(a.tokens == b.tokens for a, b in zip(dsess, psess))
    rec = {
        "harness": "keras_server.loadgen.run_paged_ab",
        "model": model, "n_sessions": n_sessions,
        "max_context": max_context, "page_size": page_size,
        "dense": dense, "paged": paged,
        "bitwise_equal": bitwise,
        "state_bytes_ratio": round(pbytes / max(dbytes, 1), 4),
        "sessions_ratio": round(
            paged["peak_active"] / max(dense_slots, 1), 3),
        "tokens_per_sec_ratio": round(
            paged["tokens_per_sec"] / max(dense["tokens_per_sec"], 1e-9),
            3),
    }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def run_spec_ab(net, draft_net, *, model: str = "decode_spec",
                slots: int = 4, max_context: int = 128,
                spec_tokens: int = 3, n_sessions: int = 16,
                prompt_len: int = 4, max_new_tokens: int = 24,
                eos_id: Optional[int] = None, seed: int = 0,
                record_path: Optional[str] = None) -> dict:
    """Plain greedy vs speculative decode with ``draft_net`` proposing.

    Identical session mix through both engines; the emitted streams must
    be bitwise equal at ANY acceptance rate (greedy argmax verify is
    exact, acceptance only moves the speed). Headline fields:
    ``tokens_per_sec_ratio`` (the spec speedup — on CPU this mostly
    tracks dispatch amortization) at the measured ``acceptance`` rate.
    """
    from .decode import DecodeEngine
    prompts, budgets = _decode_workload(
        n_sessions, _decode_vocab(net), prompt_len, max_new_tokens, seed)

    def phase(draft) -> Tuple[dict, list, dict]:
        eng = DecodeEngine(net.clone(), min_slots=slots, max_slots=slots,
                           eos_id=eos_id, max_context=max_context,
                           draft_net=draft, spec_tokens=spec_tokens)
        try:
            _decode_warmup(eng)
            t0, sessions = _offer_sessions(eng, prompts, budgets, 1e6)
            for s in sessions:
                s.result(timeout=600.0)
            res = _summarize_sessions(sessions, t0)
            st = eng.stats()
        finally:
            eng.close()
        return res, sessions, st

    greedy, gsess, _ = phase(None)
    spec, ssess, st = phase(draft_net.clone())
    bitwise = all(a.tokens == b.tokens for a, b in zip(gsess, ssess))
    rec = {
        "harness": "keras_server.loadgen.run_spec_ab",
        "model": model, "n_sessions": n_sessions, "slots": slots,
        "spec_tokens": spec_tokens,
        "greedy": greedy, "spec": spec,
        "bitwise_equal": bitwise,
        "acceptance": round(st["spec_acceptance"], 4),
        "proposed": st["spec_proposed"],
        "tokens_per_sec_ratio": round(
            spec["tokens_per_sec"] / max(greedy["tokens_per_sec"], 1e-9),
            3),
    }
    if record_path:
        os.makedirs(os.path.dirname(os.path.abspath(record_path)),
                    exist_ok=True)
        with open(record_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    return rec


def _decode_vocab(net) -> int:
    return int(net.conf.layers[-1].n_out)


def _decode_warmup(engine) -> None:
    """One throwaway session so the bucket's compile never lands on the
    measurement clock (it still lands in the phase's recompile delta)."""
    engine.submit([0], 2).result(timeout=600.0)


def _summarize_sessions(sessions, t0: float) -> dict:
    t_end = max(s.t_done for s in sessions)
    wall = max(t_end - t0, 1e-9)
    n_tokens = sum(len(s.tokens) for s in sessions)
    ttft = sorted(s.ttft_s * 1e3 for s in sessions if s.ttft_s is not None)
    itl = sorted((b - a) * 1e3 for s in sessions
                 for a, b in zip(s.token_times, s.token_times[1:]))
    return {
        "sessions": len(sessions), "tokens": n_tokens,
        "achieved_sps": round(len(sessions) / wall, 3),
        "tokens_per_sec": round(n_tokens / wall, 3),
        "duration_s": round(wall, 3),
        "ttft_p50_ms": round(percentile(ttft, 0.50), 3),
        "ttft_p99_ms": round(percentile(ttft, 0.99), 3),
        "itl_p50_ms": round(percentile(itl, 0.50), 3),
        "itl_p99_ms": round(percentile(itl, 0.99), 3),
    }


def _client_main() -> None:
    """`python -m deeplearning4j_tpu.keras_server.loadgen`: the load
    client `run_ab` launches out-of-process. Prints ONE JSON line."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--model", required=True)
    ap.add_argument("--shape", required=True,
                    help="request input shape, comma-separated")
    ap.add_argument("--qps", type=float, default=200.0)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--workers", type=int, default=32)
    ap.add_argument("--closed", action="store_true")
    ap.add_argument("--requests", type=int, default=100,
                    help="closed-loop requests per worker")
    args = ap.parse_args()
    shape = tuple(int(s) for s in args.shape.split(","))
    example = np.random.default_rng(0).normal(size=shape).astype(np.float32)
    if args.closed:
        res = run_closed_loop(args.port, args.model, example,
                              workers=args.workers,
                              requests_per_worker=args.requests,
                              host=args.host)
    else:
        res = run_open_loop(args.port, args.model, example, qps=args.qps,
                            duration_s=args.duration, workers=args.workers,
                            host=args.host)
    print(json.dumps(res), flush=True)  # lint: bare-print-ok (the one JSON line on stdout IS this subprocess's result channel — run_ab's _run_client parses it)


if __name__ == "__main__":
    _client_main()
