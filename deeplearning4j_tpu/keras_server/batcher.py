"""Dynamic micro-batching with padded shape buckets.

The single biggest serving win on an accelerator: instead of dispatching
one tiny forward per request, coalesce concurrent requests into ONE padded
batch so the device runs a large fused program. The policy:

- requests group by ``(model, per-example shape, dtype)`` — only
  shape-compatible rows share a dispatch;
- a group dispatches when it reaches ``max_batch`` OR its oldest request
  has waited ``max_latency_s`` (the latency/throughput knob);
- the concatenated rows are zero-padded up to the next **power-of-two
  batch bucket** (capped at ``max_batch``), so the compiled-program cache
  holds at most ``log2(max_batch)+1`` executables per input signature —
  steady-state serving NEVER recompiles, whatever request sizes arrive.
  Compiles are visible in the compile tracker under ``serve_predict@…``
  (``dl4j_jit_compile_total``), which is how the load test pins
  ``recompiles == bucket count``.

Padding is semantics-free: rows are independent under inference-mode
forward (running BN statistics, no dropout), so the sliced-back outputs
are **bitwise identical** to a per-request dispatch — pinned across bucket
boundaries by tests/test_serving.py.

PR 2/5/7 infrastructure rides on the dispatch loop wholesale: per-batch
latency histograms and occupancy/queue gauges (``dl4j_serve_*``), a
flight-recorder event per dispatch plus a dump on dispatch failure,
watchdog heartbeats so a wedged device yields a thread-stack bundle, and
``note_dispatch`` so the anomaly trigger can capture an XPlane trace of a
slow serve batch.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.profiler import (
    note_dispatch as _profile_note_dispatch,
)
from deeplearning4j_tpu.observability.tracing import start_span
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat

from .admission import AdmissionController, RejectedError  # noqa: F401
from .registry import ModelRegistry


def batch_bucket(n: int, max_batch: int) -> int:
    """Next power-of-two >= n, capped at max_batch."""
    if n >= max_batch:
        return max_batch
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


class _Request:
    __slots__ = ("model", "xs", "n", "key", "future", "t_enqueue", "span")

    def __init__(self, model: str, xs: Tuple[np.ndarray, ...], key: Tuple,
                 t_enqueue: float):
        self.model = model
        self.xs = xs
        self.n = int(xs[0].shape[0])
        self.key = key
        self.future: Future = Future()
        self.t_enqueue = t_enqueue
        # queue-wait span, started on the submitting thread (where the
        # request's trace context is ambient) and finished by the
        # dispatcher — contextvars don't cross threads, the slot does
        self.span = start_span("batch.queue", model=model,
                               rows=self.n)


class MicroBatcher:
    """Coalesces concurrent predict requests into padded micro-batches.

    ``submit()`` is the producer side (HTTP handler threads); one daemon
    dispatcher thread drains the queue. ``max_batch=1`` degenerates to
    unbatched serving — the load test's A/B baseline.
    """

    def __init__(self, registry: ModelRegistry, *, max_batch: int = 32,
                 max_latency_s: float = 0.002, max_queue: int = 256,
                 admission: Optional[AdmissionController] = None,
                 metrics=None, replica: Optional[int] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.registry = registry
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_s)
        #: ReplicaSet member index, or None for a standalone batcher — only
        #: adds the per-replica gauge labels and the result-dict field
        self.replica = replica
        self.admission = admission or AdmissionController(
            max_pending=max_queue, expected_latency_s=max_latency_s)
        m = metrics or global_registry()
        self._g_replica_queue = self._g_replica_occ = None
        if replica is not None:
            self._g_replica_queue = m.gauge(
                _n.SERVE_REPLICA_QUEUE_DEPTH,
                "admitted-but-unanswered requests per replica")
            self._g_replica_occ = m.gauge(
                _n.SERVE_REPLICA_OCCUPANCY,
                "rows/bucket of the replica's last dispatch")
        self._c_requests = m.counter(
            _n.SERVE_REQUESTS_TOTAL, "predict requests admitted")
        self._c_errors = m.counter(
            _n.SERVE_ERRORS_TOTAL, "predict requests failed in dispatch")
        self._c_batches = m.counter(
            _n.SERVE_BATCHES_TOTAL, "micro-batches dispatched")
        self._h_dispatch = m.histogram(
            _n.SERVE_BATCH_DISPATCH_SECONDS, "device time per micro-batch")
        self._g_occupancy = m.gauge(
            _n.SERVE_BATCH_OCCUPANCY,
            "real rows / padded bucket size of the last dispatch")
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[_Request] = []
        self._closed = False
        self._dispatches = 0
        self._occupancy_sum = 0.0
        self._buckets_seen: set = set()
        self._thread = threading.Thread(
            target=self._loop, name="serve-microbatcher", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- producer
    @staticmethod
    def _group_key(model: str, xs: Tuple[np.ndarray, ...]) -> Tuple:
        return (model,) + tuple((x.shape[1:], str(x.dtype)) for x in xs)

    def submit(self, model: str, x, *, priority: str = "high",
               tenant: str = "-") -> Future:
        """Queue one request (``x`` carries a leading batch axis; a single
        example must arrive as shape ``[1, ...]``; a multi-input graph
        takes a list/tuple of arrays sharing the leading axis). Raises
        :class:`RejectedError` when admission refuses (HTTP 429).
        ``priority``/``tenant`` flow to admission: under saturation, low
        priorities are shed before high ones (see ``admission.py``)."""
        if isinstance(x, (list, tuple)):
            xs = tuple(np.asarray(a) for a in x)
            if not xs:
                raise ValueError("empty input list")
        else:
            xs = (np.asarray(x),)
        for a in xs:
            if a.ndim < 2:
                raise ValueError(
                    f"request needs a leading batch axis, got shape "
                    f"{a.shape}")
        if len({a.shape[0] for a in xs}) != 1:
            raise ValueError(
                "multi-input request arrays must share the leading batch "
                f"axis, got {[a.shape[0] for a in xs]}")
        if xs[0].shape[0] > self.max_batch:
            raise ValueError(
                f"request batch {xs[0].shape[0]} exceeds max_batch "
                f"{self.max_batch}; split it client-side")
        self.admission.admit(priority=priority, tenant=tenant)
        self._c_requests.labels(model=model).inc()
        req = _Request(model, xs, self._group_key(model, xs),
                       time.perf_counter())
        with self._cond:
            if self._closed:
                self.admission.release()
                req.span.set_status("error").finish()
                raise RuntimeError("MicroBatcher is closed")
            self._queue.append(req)
            self._cond.notify()
        if self._g_replica_queue is not None:
            self._g_replica_queue.labels(
                replica=str(self.replica)).set(self.admission.pending)
        return req.future

    # ------------------------------------------------------------ dispatcher
    #: requires-lock: _cond
    def _take_group(self) -> Optional[List[_Request]]:
        """Under the lock: wait for work, honor the fill-or-deadline policy,
        then cut one shape-compatible group from the queue."""
        while True:
            if self._closed and not self._queue:
                return None
            if not self._queue:
                self._cond.wait(0.05)
                continue
            head = self._queue[0]
            rows = 0
            group: List[_Request] = []
            for r in self._queue:
                if r.key == head.key and rows + r.n <= self.max_batch:
                    group.append(r)
                    rows += r.n
                    if rows == self.max_batch:
                        break
            deadline = head.t_enqueue + self.max_latency_s
            now = time.perf_counter()
            if rows < self.max_batch and now < deadline \
                    and not self._closed:
                self._cond.wait(deadline - now)
                continue
            # one O(queue) rebuild, not O(queue) remove() per member — at
            # saturation depth the quadratic scan would eat the GIL budget
            # the batching is supposed to win back
            taken = set(map(id, group))
            self._queue = [r for r in self._queue if id(r) not in taken]
            return group

    def _dispatch(self, group: List[_Request]) -> None:
        rows = sum(r.n for r in group)
        bucket = batch_bucket(rows, self.max_batch)
        # close each member's queue-wait span at the group cut, then open
        # ONE dispatch span on its own trace that *links* the N member
        # traces (OTel batch-consumer fan-in: no single parent is honest)
        links = []
        for r in group:
            r.span.set_attr(bucket=bucket)
            ref = r.span.ref()
            if ref is not None:
                links.append(ref)
            r.span.finish()
        dspan = start_span("batch.dispatch", links=tuple(links),
                           model=group[0].model, rows=rows, bucket=bucket,
                           requests=len(group))
        if self.replica is not None:
            dspan.set_attr(replica=self.replica)
        try:
            self._dispatch_inner(group, rows, bucket, dspan)
        finally:
            dspan.finish()

    def _dispatch_inner(self, group: List[_Request], rows: int,
                        bucket: int, dspan) -> None:
        try:
            # (replica, version) resolve HERE, at dispatch time: the atomic
            # active pointer means a group enqueued against version N can
            # legally dispatch against N+1 — each is internally consistent
            mv = self.registry.active(group[0].model)
            dspan.set_attr(
                version=mv.version,
                compile_cache_hit=getattr(mv.predict_fn, "cache_hit", None))
            n_inputs = len(group[0].xs)
            xs = []
            for j in range(n_inputs):
                x = np.concatenate([r.xs[j] for r in group], axis=0)
                if bucket > rows:
                    pad = np.zeros((bucket - rows,) + x.shape[1:], x.dtype)
                    x = np.concatenate([x, pad], axis=0)
                xs.append(x)
            t0 = time.perf_counter()
            raw = mv.predict_fn(*xs)
            multi_out = isinstance(raw, (list, tuple))
            if not multi_out:
                raw = [raw]
            # lint: host-sync-in-hot-loop-ok (serving must materialize the response; the sync IS the dispatch being timed)
            outs = [np.asarray(o) for o in raw]
            dt = time.perf_counter() - t0
        except Exception as e:
            self._c_errors.inc(len(group))
            dspan.set_status("error").set_attr(error=repr(e))
            _flight_recorder().dump(
                reason="serve-dispatch-error",
                extra={"model": group[0].model, "rows": rows,
                       "bucket": bucket, "error": repr(e)})
            for r in group:
                r.future.set_exception(e)
            return
        finally:
            self.admission.release(len(group))
            if self._g_replica_queue is not None:
                self._g_replica_queue.labels(
                    replica=str(self.replica)).set(self.admission.pending)
        occupancy = rows / bucket
        dspan.set_attr(dispatch_s=round(dt, 6), occupancy=round(occupancy, 4))
        # a serve dispatch advances the step clock like a fit dispatch, so
        # the recompile-storm window is measured in dispatches (bucket
        # warm-up compiles are expected; steady-state compiles are the bug)
        _compile_tracker().note_step()
        self._c_batches.labels(model=mv.name).inc()
        self._h_dispatch.observe(dt)
        self._g_occupancy.set(occupancy)
        if self._g_replica_occ is not None:
            self._g_replica_occ.labels(
                replica=str(self.replica)).set(occupancy)
        _profile_note_dispatch(dt)
        with self._lock:
            self._dispatches += 1
            self._occupancy_sum += occupancy
            self._buckets_seen.add((group[0].key, bucket))
            n_dispatch = self._dispatches
        _flight_recorder().record(
            "serve_batch", model=mv.name, version=mv.version, rows=rows,
            bucket=bucket, requests=len(group), dispatch_s=dt,
            **({"replica": self.replica} if self.replica is not None else {}))
        _wd_beat(n_dispatch)
        off = 0
        for r in group:
            pred = [o[off:off + r.n] for o in outs]
            r.future.set_result(
                {"predictions": pred if multi_out else pred[0],
                 "model": mv.name, "version": mv.version,
                 "batch_rows": rows, "bucket": bucket,
                 "replica": self.replica})
            off += r.n

    def _loop(self) -> None:
        while True:
            with self._cond:
                group = self._take_group()
            if group is None:
                return
            self._dispatch(group)

    # -------------------------------------------------------------- control
    def stats(self) -> dict:
        with self._lock:
            return {
                "queue_depth": len(self._queue),
                "pending": self.admission.pending,
                "max_queue": self.admission.max_pending,
                "rejected": self.admission.rejected,
                "dispatches": self._dispatches,
                "mean_occupancy": (self._occupancy_sum / self._dispatches
                                   if self._dispatches else 0.0),
                "buckets": sorted(
                    (list(map(str, key)), bucket)
                    for key, bucket in self._buckets_seen),
                "bucket_count": len(self._buckets_seen),
                "max_batch": self.max_batch,
                "max_latency_s": self.max_latency_s,
                "replica": self.replica,
            }

    def close(self, timeout_s: float = 5.0) -> None:
        """Stop accepting work; the dispatcher drains the queue first."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout_s)
