"""SLO-driven autoscaler: burn rates in, fleet size out.

The PR 17 ``SLOEngine`` computes error-budget burn rates nobody acted on;
this control loop closes it. Each tick reads two live signals:

- **SLO burn** — ``SLOEngine.evaluate()``'s short-window burn rate per
  objective (latency p99, TTFT, availability). Burn > 1 means the fleet
  is spending error budget faster than the objective allows;
- **queue pressure** — mean admitted-but-unanswered fraction across
  replicas (``dl4j_serve_replica_queue_depth`` / max_queue), the leading
  indicator that fires *before* latency histograms catch up.

and drives ``ReplicaSet.add_replica()`` / ``remove_replica()`` under
hysteresis so the loop never flaps:

- **cooldown**: at most one scale event per ``cooldown_s`` window;
- **one step at a time**: never jumps more than one replica per decision;
- **bounds**: fleet size stays in ``[min_replicas, max_replicas]``;
- **sustained headroom**: scale-in requires ``headroom_ticks`` consecutive
  low-pressure ticks, not one quiet sample.

Scale-out goes through the warm path (``add_replica`` pre-builds every
bucket program against the persistent compile cache before the replica is
routable) so capacity arrives in tens of milliseconds, not a compile
storm; scale-in is the drain-without-loss idiom. The measured
decision-to-routable wall time is exported as
``last_scale_out_latency_s`` in :meth:`status`.

**Zombie sweep.** With a ``cloud.MembershipOracle`` attached to the set,
every tick first heartbeats in-set replicas and evicts any whose lease no
longer validates (a fenced replica serves nothing anyway — the router
skips it), backfilling outside the cooldown if that drops the fleet below
``min_replicas``. Lease fencing is correctness; hysteresis only governs
capacity.

``clock`` is injectable so hysteresis math is unit-testable with a fake
clock, and ``slo_engine`` is duck-typed (anything with ``evaluate()``)
for the same reason.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)


class Autoscaler:
    """Drives a :class:`~.replica.ReplicaSet`'s size from SLO burn rates
    and queue pressure, with hysteresis."""

    def __init__(self, replica_set, *, slo_engine=None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_s: float = 30.0, interval_s: float = 2.0,
                 scale_out_burn: float = 1.0, scale_in_burn: float = 0.5,
                 queue_high: float = 0.5, queue_low: float = 0.1,
                 headroom_ticks: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.replica_set = replica_set
        self.slo_engine = slo_engine
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.scale_out_burn = float(scale_out_burn)
        self.scale_in_burn = float(scale_in_burn)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.headroom_ticks = int(headroom_ticks)
        self.clock = clock
        self._lock = threading.Lock()
        self._last_scale_at: Optional[float] = None
        self._low_ticks = 0
        self._ticks = 0
        self._last_decision = "none"
        self._last_reason = "startup"
        self._events: List[dict] = []
        self.last_scale_out_latency_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- signals
    def _slo_signals(self) -> tuple:
        """(max short-window burn rate, any objective alerting)."""
        if self.slo_engine is None:
            return 0.0, False
        burn, alerting = 0.0, False
        for obj in self.slo_engine.evaluate():
            windows = obj.get("windows") or []
            if windows:
                burn = max(burn, float(windows[0].get("burn_rate", 0.0)))
            alerting = alerting or bool(obj.get("alerting"))
        return burn, alerting

    def _queue_fraction(self) -> float:
        """Mean admitted/max_pending across replicas — 1.0 is saturated."""
        fracs = []
        for r in self.replica_set.replicas:
            cap = r.batcher.admission.max_pending
            fracs.append(r.queue_depth() / cap if cap else 0.0)
        return sum(fracs) / len(fracs) if fracs else 0.0

    # ---------------------------------------------------------------- tick
    def _in_cooldown(self, now: float) -> bool:
        return (self._last_scale_at is not None
                and now - self._last_scale_at < self.cooldown_s)

    def _record(self, direction: str, reason: str, now: float,
                size: int, latency_s: Optional[float] = None) -> None:
        ev = {"direction": direction, "reason": reason, "t": now,
              "size": size}
        if latency_s is not None:
            ev["scale_out_latency_s"] = latency_s
        with self._lock:
            self._last_decision = direction
            self._last_reason = reason
            self._events.append(ev)
            del self._events[:-64]
        _flight_recorder().record(
            "fleet_scale", direction=direction, reason=reason, size=size)

    def _scale_out(self, reason: str, now: float) -> None:
        t0 = self.clock()
        self.replica_set.add_replica(reason=reason)
        latency = self.clock() - t0
        self.last_scale_out_latency_s = latency
        self._last_scale_at = now
        self._low_ticks = 0
        self._record("out", reason, now, self.replica_set.n_replicas,
                     latency_s=latency)

    def _scale_in(self, reason: str, now: float) -> None:
        self.replica_set.remove_replica(reason=reason)
        self._last_scale_at = now
        self._low_ticks = 0
        self._record("in", reason, now, self.replica_set.n_replicas)

    def tick(self, now: Optional[float] = None) -> str:
        """One control decision; returns ``"out"``, ``"in"`` or
        ``"none"``. Safe to call from a test without :meth:`start`."""
        now = self.clock() if now is None else now
        self._ticks += 1
        rs = self.replica_set
        # 1) lease fencing is correctness, not capacity: sweep zombies
        #    first, outside the hysteresis window
        rs.heartbeat()
        for zombie in rs.fenced_replicas():
            try:
                rs.remove_replica(zombie.index, reason="lease-fenced")
            except ValueError:
                break   # last/primary replica: nothing to fence to
        while rs.n_replicas < self.min_replicas:
            self._scale_out("replace-fenced", now)
        # 2) capacity signals
        burn, alerting = self._slo_signals()
        qfrac = self._queue_fraction()
        # 3) hysteresis: one step, cooldown, bounds
        if self._in_cooldown(now):
            return "none"
        if rs.n_replicas < self.max_replicas and (
                alerting or burn >= self.scale_out_burn
                or qfrac > self.queue_high):
            reason = "queue-depth" if qfrac > self.queue_high \
                and not (alerting or burn >= self.scale_out_burn) \
                else "slo-burn"
            self._scale_out(reason, now)
            return "out"
        if rs.n_replicas > self.min_replicas and burn < self.scale_in_burn \
                and qfrac < self.queue_low:
            self._low_ticks += 1
            if self._low_ticks >= self.headroom_ticks:
                self._scale_in("headroom", now)
                return "in"
        else:
            self._low_ticks = 0
        return "none"

    # -------------------------------------------------------------- control
    def start(self, interval_s: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        if interval_s is not None:
            self.interval_s = float(interval_s)
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-autoscaler", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # a control-loop crash must not take serving down; the
                # flight recorder keeps the scale-event history for triage
                _flight_recorder().record("fleet_scale_error")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(5.0)

    def status(self) -> dict:
        """The /serve/status "autoscaler" block."""
        now = self.clock()
        with self._lock:
            events = list(self._events[-16:])
            decision, reason = self._last_decision, self._last_reason
        cooldown_left = 0.0
        if self._last_scale_at is not None:
            cooldown_left = max(
                0.0, self.cooldown_s - (now - self._last_scale_at))
        return {
            "running": self._thread is not None,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "n_replicas": self.replica_set.n_replicas,
            "cooldown_s": self.cooldown_s,
            "cooldown_remaining_s": round(cooldown_left, 3),
            "interval_s": self.interval_s,
            "ticks": self._ticks,
            "last_decision": decision,
            "last_reason": reason,
            "last_scale_out_latency_s": self.last_scale_out_latency_s,
            "events": events,
        }
