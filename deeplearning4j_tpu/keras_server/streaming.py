"""Streaming timestep inference over the ``rnn_time_step`` seam.

The reference's serving story for recurrent models is ``rnnTimeStep`` plus
``rnnGet/SetPreviousState`` — feed one timestep, carry hidden state across
calls, hand state around for session affinity. This module turns that seam
into server-side sessions:

- ONE streaming clone per (model, version) — cloned once so streaming
  state never touches the registry's pinned predict snapshot, and shared
  across sessions so the ``rnn_time_step`` program compiles once per
  distinct batch shape, not once per session;
- per-session state is parked host-side between calls via
  ``rnn_get_previous_state``/``rnn_set_previous_state`` (exactly the
  reference's serving-handoff contract), swapped in under the model lock
  for each step;
- sessions idle past ``ttl_s`` are evicted on the next touch, and eviction
  **releases the parked device state block** — ``delete()`` on every leaf,
  after un-aliasing the clone's live ``_rnn_state`` (the most recently
  stepped session's parked tree IS that attribute, so dropping the dict
  entry alone would keep its buffers resident). The churn regression in
  tests/test_decode.py pins that 1k evicted sessions do not grow
  device-resident bytes.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry

from .registry import ModelRegistry


class _StreamModel:
    """The shared streaming clone + its per-session parked states."""

    def __init__(self, net):
        self.net = net.clone()
        self.lock = threading.Lock()
        #: session id -> (parked rnn state, last-touch monotonic time)
        self.states: Dict[str, Tuple[object, float]] = {}


class StreamSessions:
    """Server-side rnnTimeStep sessions with TTL eviction."""

    def __init__(self, registry: ModelRegistry, ttl_s: float = 300.0,
                 metrics=None):
        self.registry = registry
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._models: Dict[Tuple[str, str], _StreamModel] = {}
        m = metrics or global_registry()
        self._g_sessions = m.gauge(
            _n.SERVE_STREAM_SESSIONS, "live streaming sessions")
        self._c_steps = m.counter(
            _n.SERVE_STREAM_STEPS_TOTAL, "streamed timesteps served")
        self._c_evictions = m.counter(
            _n.SERVE_EVICTIONS_TOTAL, "slot evictions by reason")

    def _model(self, name: str) -> Tuple[_StreamModel, str]:
        mv = self.registry.active(name)
        if not mv.streaming_capable:
            raise ValueError(f"model {name!r} has no rnn_time_step seam")
        key = (mv.name, mv.version)
        with self._lock:
            sm = self._models.get(key)
            if sm is None:
                sm = self._models[key] = _StreamModel(mv.net)
            # hot swap moved the active pointer: drop this model's stale-
            # version clones once they park no sessions (new steps resolve
            # the active version, so an empty stale clone can never refill)
            for (n0, v0), old in list(self._models.items()):
                if n0 == mv.name and v0 != mv.version and not old.states:
                    del self._models[(n0, v0)]
            return sm, mv.version

    @staticmethod
    def _release_state(sm: _StreamModel, state) -> None:
        """Eagerly free a parked state's device buffers (caller holds
        ``sm.lock``). The parked tree of the most recently stepped session
        aliases the clone's live ``_rnn_state`` (``rnn_get_previous_state``
        returns it by reference), so that alias is cleared first; then every
        leaf is ``delete()``d instead of waiting on the GC — parked blocks
        are the serving tier's HBM, not garbage."""
        if sm.net.rnn_get_previous_state() is state:
            sm.net.rnn_clear_previous_state()
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "is_deleted") and not leaf.is_deleted():
                leaf.delete()

    def _evict_expired(self, sm: _StreamModel, now: float) -> None:
        for sid, (state, t) in list(sm.states.items()):
            if now - t > self.ttl_s:
                del sm.states[sid]
                self._release_state(sm, state)
                self._c_evictions.labels(reason="ttl").inc()

    def _session_count(self) -> int:
        with self._lock:
            return sum(len(sm.states) for sm in self._models.values())

    def step(self, model: str, session: str, x) -> dict:
        """Advance one session by one (or more) timesteps.

        ``x``: ``[B, T, F]`` (or ``[B, F]``, treated as T=1). Returns the
        output for the LAST timestep plus the model version serving the
        session. State persists server-side under ``session``.
        """
        x = np.asarray(x)
        if x.ndim == 2:
            x = x[:, None, :]
        if x.ndim != 3:
            raise ValueError(
                f"streaming input must be [B,T,F] or [B,F], got {x.shape}")
        sm, version = self._model(model)
        with sm.lock:
            now = time.monotonic()
            self._evict_expired(sm, now)
            parked = sm.states.get(session)
            sm.net.rnn_set_previous_state(
                parked[0] if parked is not None else None)
            out = sm.net.rnn_time_step(x)
            if isinstance(out, list):  # ComputationGraph returns [outputs]
                out = out[0]
            sm.states[session] = (sm.net.rnn_get_previous_state(), now)
        self._c_steps.labels(model=model).inc(int(x.shape[1]))
        self._g_sessions.set(self._session_count())
        return {"output": np.asarray(out), "model": model,
                "version": version, "session": session,
                "timesteps": int(x.shape[1])}

    def reset(self, model: str, session: str) -> bool:
        """Drop a session's parked state (True if it existed)."""
        try:
            sm, _ = self._model(model)
        except KeyError:
            return False
        with sm.lock:
            parked = sm.states.pop(session, None)
            existed = parked is not None
            if existed:
                self._release_state(sm, parked[0])
                self._c_evictions.labels(reason="reset").inc()
        self._g_sessions.set(self._session_count())
        return existed

    def status(self) -> dict:
        with self._lock:
            return {
                f"{name}@{version}": sorted(sm.states)
                for (name, version), sm in sorted(self._models.items())}
