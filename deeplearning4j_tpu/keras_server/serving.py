"""HTTP inference front-end: ``/v1/predict`` + streaming + status.

Same stack as the training UI (``ui/server.py``): stdlib
``ThreadingHTTPServer``, one handler thread per connection, loopback bind
by default. The handler threads are pure producers — every predict request
funnels through the :class:`MicroBatcher`'s single dispatcher, so device
concurrency is one padded program at a time regardless of client fan-in.

Routes:

- ``POST /v1/predict``  body ``{"model": m, "inputs": [[...], ...]}`` —
  micro-batched forward; 200 with ``{"predictions", "model", "version",
  "batched_with", "bucket"}``, 429 + ``Retry-After`` on admission
  overflow, 404 for unknown models, 503 on dispatch timeout.
- ``POST /v1/stream``   body ``{"model": m, "session": s, "inputs":
  [B,T,F]}`` — newline-delimited JSON, ONE line per timestep as it is
  computed over the ``rnnTimeStep`` seam; hidden state persists
  server-side under ``session`` across requests.
- ``POST /v1/stream/reset`` — drop a session's parked state.
- ``POST /v1/generate`` body ``{"model": m, "prompt": [ids],
  "max_new_tokens": n}`` — autoregressive generation over the continuous-
  batching :class:`DecodeEngine` (decode.py): newline-delimited JSON, ONE
  line per generated token as the persistent decode loop emits it; the
  request shares slot capacity with every other in-flight generation.
- ``GET /serve/status`` — models/versions, queue depth, bucket occupancy
  (the same payload the training UI proxies).
- ``GET /serve/traces`` / ``GET /serve/traces/<id>`` — tail-sampled trace
  summaries / one full span tree (observability/tracing.py).
- ``GET /serve/slo`` — SLO burn-rate evaluation with trace exemplars
  (observability/slo.py).
- ``GET /metrics`` — Prometheus text (standalone deployments; the UI
  server exposes the same registry).

Per-route latency lands in ``dl4j_serve_request_seconds{route=...}``.
Every POST extracts (or mints) a W3C ``traceparent``, makes it the
handler thread's ambient trace context, and echoes the root span's id
back in the response headers.
"""
from __future__ import annotations

import json
import queue
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.federation import (
    fleet_metrics_text, fleet_status, register_status_provider,
    trigger_fleet_dump,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.slo import SLOEngine
from deeplearning4j_tpu.observability.tracing import (
    TRACEPARENT_HEADER, global_trace_store, parse_traceparent, trace_span,
)

from .admission import RejectedError, normalize_priority
from .autoscaler import Autoscaler
from .batcher import MicroBatcher
from .decode import DecodeEngine
from .registry import ModelRegistry, global_model_registry
from .replica import ReplicaSet
from .streaming import StreamSessions

#: request tags for priority-aware shedding under saturation
PRIORITY_HEADER = "X-DL4J-Priority"
TENANT_HEADER = "X-DL4J-Tenant"


class _ServeHandler(BaseHTTPRequestHandler):
    engine: "InferenceServer"  # bound via type() subclass

    # keep-alive: without this the stdlib default (HTTP/1.0) closes the
    # socket after EVERY response, so each request pays a TCP connect plus
    # a fresh handler thread — at serving rates that reconnect tax dwarfs
    # the model dispatch the micro-batcher is amortizing. Every response
    # below carries Content-Length (or proper chunked framing), which
    # HTTP/1.1 persistence requires.
    protocol_version = "HTTP/1.1"

    # small request/response pairs on a persistent connection are the
    # Nagle + delayed-ACK worst case (40ms stalls per roundtrip); serving
    # traffic is latency-critical, so push segments out immediately
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # silence request logging
        pass

    # ------------------------------------------------------------- helpers
    def _json(self, obj, code=200, headers=()):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        tp = getattr(self, "_traceparent", "")
        if tp:
            self.send_header(TRACEPARENT_HEADER, tp)
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        if n <= 0:
            return {}
        raw = self.rfile.read(n)
        obj = json.loads(raw.decode())
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    # -------------------------------------------------------------- routes
    def do_GET(self):
        path = urlparse(self.path).path
        self._traceparent = ""
        if path == "/serve/status":
            self._json(self.engine.status())
        elif path == "/serve/traces":
            self._json({"traces": global_trace_store().list()})
        elif path.startswith("/serve/traces/"):
            trace_id = path.rsplit("/", 1)[1]
            rec = global_trace_store().get(trace_id)
            if rec is None:
                self._json({"error": f"unknown trace {trace_id}"}, code=404)
            else:
                self._json(rec)
        elif path == "/serve/slo":
            self._json({"slo": self.engine.slo.evaluate()})
        elif path == "/metrics":
            body = global_registry().prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/fleet/metrics":
            # the federated view: every member's series, merged — NOT this
            # process's registry (that is what /metrics is for)
            body = fleet_metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/fleet/status":
            self._json(fleet_status())
        else:
            self._json({"error": f"unknown route {path}"}, code=404)

    def do_POST(self):
        path = urlparse(self.path).path
        t0 = time.perf_counter()
        # extract the caller's trace context (or mint a fresh trace), make
        # it ambient for everything this handler thread does, and echo the
        # ROOT span's traceparent on every response so the client can fetch
        # /serve/traces/<id> afterwards
        parent = parse_traceparent(self.headers.get(TRACEPARENT_HEADER))
        root = trace_span(f"http {path}", parent=parent, route=path)
        self._trace = root
        self._traceparent = root.traceparent()
        try:
            with root:
                try:
                    if path == "/v1/predict":
                        self._predict()
                    elif path == "/v1/stream":
                        self._stream()
                    elif path == "/v1/generate":
                        self._generate()
                    elif path == "/v1/stream/reset":
                        req = self._body()
                        existed = self.engine.sessions.reset(
                            str(req.get("model", "")),
                            str(req.get("session", "")))
                        self._json({"reset": existed})
                    elif path == "/fleet/dump":
                        req = self._body()
                        bundle = trigger_fleet_dump(
                            str(req.get("reason", "api")),
                            force=bool(req.get("force")))
                        self._json({"ok": bundle is not None,
                                    "path": bundle})
                    else:
                        self._json({"error": f"unknown route {path}"},
                                   code=404)
                except RejectedError as e:
                    root.set_status("rejected")
                    root.set_attr(http_status=429)
                    self._json(
                        {"error": str(e), "pending": e.pending,
                         "limit": e.limit},
                        code=429,
                        headers=(("Retry-After",
                                  f"{max(e.retry_after_s, 0.001):.3f}"),))
                except KeyError as e:
                    root.set_attr(http_status=404)
                    self._json({"error": f"unknown model: {e}"}, code=404)
                except (ValueError, json.JSONDecodeError) as e:
                    root.set_attr(http_status=400)
                    self._json({"error": str(e)}, code=400)
                except TimeoutError as e:
                    root.set_status("error")
                    root.set_attr(http_status=503)
                    self._json({"error": f"dispatch timed out: {e}"},
                               code=503)
        finally:
            dt = time.perf_counter() - t0
            self.engine._h_request.labels(route=path).observe(dt)
            if root.trace_id:
                global_trace_store().put_exemplar(
                    _n.SERVE_REQUEST_SECONDS, dt, root.trace_id)

    @staticmethod
    def _inputs(req: dict) -> np.ndarray:
        if "inputs" not in req:
            raise ValueError('request body needs an "inputs" field')
        return np.asarray(req["inputs"], dtype=np.float32)

    def _predict(self) -> None:
        req = self._body()
        model = str(req.get("model", ""))
        x = self._inputs(req)
        if x.ndim == 1:
            x = x[None, :]
        self.engine.registry.active(model)  # 404 before queueing
        # priority/tenant headers feed saturation shedding (admission.py);
        # untagged requests default to the full budget ("high")
        priority = normalize_priority(self.headers.get(PRIORITY_HEADER))
        tenant = str(self.headers.get(TENANT_HEADER) or "-")
        fut = self.engine.submit_predict(model, x, priority=priority,
                                         tenant=tenant)
        try:
            res = fut.result(timeout=self.engine.request_timeout_s)
        except (_FutureTimeout, TimeoutError):
            raise TimeoutError(
                f"no dispatch within {self.engine.request_timeout_s}s")
        except Exception as e:
            tr = getattr(self, "_trace", None)
            if tr is not None:
                tr.set_status("error").set_attr(http_status=500)
            self._json({"error": f"{type(e).__name__}: {e}"}, code=500)
            return
        payload = {
            "predictions": np.asarray(res["predictions"]).tolist(),
            "model": res["model"], "version": res["version"],
            "batched_with": res["batch_rows"], "bucket": res["bucket"]}
        if res.get("replica") is not None:
            payload["replica"] = res["replica"]
        self._json(payload)

    def _stream(self) -> None:
        req = self._body()
        model = str(req.get("model", ""))
        session = str(req.get("session") or f"conn-{id(self.connection)}")
        x = self._inputs(req)
        if x.ndim == 2:
            x = x[:, None, :]
        if x.ndim != 3:
            raise ValueError(
                f"stream inputs must be [B,T,F] or [B,F], got {x.shape}")
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        if getattr(self, "_traceparent", ""):
            self.send_header(TRACEPARENT_HEADER, self._traceparent)
        self.end_headers()

        def chunk(obj: dict) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        for t in range(x.shape[1]):
            step = self.engine.sessions.step(model, session, x[:, t:t + 1, :])
            chunk({"t": t, "output": step["output"][:, -1, :].tolist(),
                   "version": step["version"]})
        chunk({"done": True, "session": session, "timesteps": int(x.shape[1])})
        self.wfile.write(b"0\r\n\r\n")

    def _generate(self) -> None:
        req = self._body()
        model = str(req.get("model", ""))
        prompt = req.get("prompt")
        if not isinstance(prompt, list) or not prompt:
            raise ValueError(
                'generate needs a non-empty "prompt" list of token ids')
        max_new = int(req.get("max_new_tokens", 32))
        eng = self.engine.decoder(model)
        tokens_q: "queue.Queue" = queue.Queue()
        sess = eng.submit(prompt, max_new,
                          stream=lambda sid, tok, t: tokens_q.put((tok, t)))
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        if getattr(self, "_traceparent", ""):
            self.send_header(TRACEPARENT_HEADER, self._traceparent)
        self.end_headers()

        def chunk(obj: dict) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        i = 0
        deadline = time.monotonic() + self.engine.request_timeout_s
        while True:
            try:
                tok, _t = tokens_q.get(timeout=0.02)
            except queue.Empty:
                if sess.done.is_set() and tokens_q.empty():
                    break
                if time.monotonic() > deadline:
                    chunk({"error": "generation timed out"})
                    break
                continue
            chunk({"i": i, "token": int(tok)})
            i += 1
        chunk({"done": True, "tokens": sess.tokens,
               "reason": sess.evict_reason,
               "ttft_s": sess.ttft_s})
        self.wfile.write(b"0\r\n\r\n")


class InferenceServer:
    """The serving engine: registry + micro-batcher + HTTP front-end."""

    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 max_batch: int = 32, max_latency_s: float = 0.002,
                 max_queue: int = 256, request_timeout_s: float = 30.0,
                 stream_ttl_s: float = 300.0, decode_min_slots: int = 2,
                 decode_max_slots: int = 16, decode_max_context: int = 256,
                 decode_eos_id: Optional[int] = None,
                 decode_kv: str = "dense", decode_page_size: int = 16,
                 decode_pool_pages: Optional[int] = None,
                 decode_spec_draft: Optional[str] = None,
                 decode_spec_tokens: int = 3,
                 replicas: int = 1, sharding: Optional[str] = None,
                 replica_devices=None,
                 replica_mesh_axes: Optional[dict] = None,
                 warmup: bool = False, autoscale: bool = False,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 autoscale_cooldown_s: float = 30.0,
                 autoscale_interval_s: float = 2.0):
        self.replica_set: Optional[ReplicaSet] = None
        self.autoscaler = None
        self._membership = None
        if replicas > 1 or sharding is not None or autoscale:
            if registry is not None:
                raise ValueError(
                    "replica mode owns its per-replica registries; pass "
                    "registry=None and register through server.register()")
            if autoscale:
                # serving replicas are fenced members exactly like elastic
                # training workers: lease lapse = out of the router
                from deeplearning4j_tpu.cloud import MembershipOracle
                self._membership = MembershipOracle(role="replica")
            self.replica_set = ReplicaSet(
                replicas, sharding=sharding, devices=replica_devices,
                mesh_axes=replica_mesh_axes, max_batch=max_batch,
                max_latency_s=max_latency_s, max_queue=max_queue,
                warmup=warmup, membership=self._membership)
            # replica 0's registry is the front door's catalog (404 check,
            # streaming, decode) — every roll keeps all replicas in sync
            self.registry = self.replica_set.primary_registry
            self.batcher: Optional[MicroBatcher] = None
        else:
            self.registry = registry or global_model_registry()
            if warmup:
                # opt THIS server's registrations into AOT bucket warmup
                # (works for a caller-supplied registry too)
                self.registry.warmup_max_batch = max_batch
            self.batcher = MicroBatcher(
                self.registry, max_batch=max_batch,
                max_latency_s=max_latency_s, max_queue=max_queue)
        self.sessions = StreamSessions(self.registry, ttl_s=stream_ttl_s)
        self.request_timeout_s = float(request_timeout_s)
        self._decode_opts = dict(
            min_slots=decode_min_slots, max_slots=decode_max_slots,
            max_context=decode_max_context, eos_id=decode_eos_id,
            kv=decode_kv, page_size=decode_page_size,
            n_pages=decode_pool_pages, spec_tokens=decode_spec_tokens)
        #: explicit draft-model name for every decoder; None falls back to
        #: the registry's per-target link (registry.draft_of)
        self._decode_spec_draft = decode_spec_draft
        self._decoders: dict = {}
        self._dec_lock = threading.Lock()
        self._h_request = global_registry().histogram(
            _n.SERVE_REQUEST_SECONDS, "HTTP request latency per route")
        #: the error-budget engine over this process's serve metrics;
        #: /serve/slo evaluates on demand, start() spins the ticker so
        #: burn alerts fire (and dump flight-recorder bundles) unscraped
        self.slo = SLOEngine()
        if autoscale:
            self.autoscaler = Autoscaler(
                self.replica_set, slo_engine=self.slo,
                min_replicas=min_replicas or 1,
                max_replicas=max_replicas or max(replicas, 8),
                cooldown_s=autoscale_cooldown_s,
                interval_s=autoscale_interval_s)
        handler = type("BoundServeHandler", (_ServeHandler,),
                       {"engine": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True)
        self._thread.start()
        self.slo.start()
        if self.autoscaler is not None:
            self.autoscaler.start()
        _set_active_server(self)
        register_status_provider("serving", self.status)
        return self

    def register(self, name: str, net, version: Optional[str] = None,
                 quant: Optional[str] = None):
        """Register a model for serving: the rolling replica path when in
        replica mode, the plain registry otherwise."""
        if self.replica_set is not None:
            return self.replica_set.register(name, net, version=version,
                                             quant=quant)
        return self.registry.register(name, net, version=version,
                                      quant=quant)

    def submit_predict(self, model: str, x, *, priority: str = "high",
                       tenant: str = "-"):
        """The handler's dispatch seam: least-queue-depth routing across
        the ReplicaSet, or the single micro-batcher. ``priority``/
        ``tenant`` flow to admission for saturation shedding."""
        if self.replica_set is not None:
            return self.replica_set.submit(model, x, priority=priority,
                                           tenant=tenant)
        return self.batcher.submit(model, x, priority=priority,
                                   tenant=tenant)

    def decoder(self, model: str) -> DecodeEngine:
        """The continuous-batching decode engine for ``model``'s active
        version, created lazily and shared by every /v1/generate request —
        the slot tensor IS the cross-request batch. A version inherits its
        int8 serving DtypePolicy from how it was registered. When a draft
        model is linked (server option or registry.link_draft), the engine
        decodes speculatively against the draft's active version — the key
        carries both versions, so hot-swapping EITHER retires the engine."""
        mv = self.registry.active(model)
        draft_name = self._decode_spec_draft \
            or self.registry.draft_of(model)
        draft_mv = (self.registry.active(draft_name)
                    if draft_name is not None else None)
        key = (mv.name, mv.version,
               None if draft_mv is None else draft_mv.version)
        with self._dec_lock:
            eng = self._decoders.get(key)
            if eng is None:
                eng = self._decoders[key] = DecodeEngine(
                    mv.net, quant=mv.quant,
                    draft_net=None if draft_mv is None else draft_mv.net,
                    **self._decode_opts)
            # hot swap moved the active pointer: retire this model's
            # stale-version engines once they have nothing in flight (their
            # pinned params + slot state are dead weight after a roll)
            for k0, stale in list(self._decoders.items()):
                if k0[0] == mv.name and k0 != key and stale.idle():
                    stale.close()
                    del self._decoders[k0]
            return eng

    def stop(self) -> None:
        register_status_provider("serving", None)
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.slo.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self.batcher is not None:
            self.batcher.close()
        if self.replica_set is not None:
            self.replica_set.close()
        with self._dec_lock:
            for eng in self._decoders.values():
                eng.close()
            self._decoders.clear()
        _set_active_server(None, only_if=self)

    def status(self) -> dict:
        """Everything /serve/status (here and on the training UI) shows."""
        with self._dec_lock:
            decode = {
                f"{name}@{version}"
                + (f"+draft@{dv}" if dv is not None else ""): eng.stats()
                for (name, version, dv), eng
                in sorted(self._decoders.items(),
                          key=lambda kv: (kv[0][0], kv[0][1],
                                          kv[0][2] or ""))}
        st = {
            **self.registry.status(),
            "queue": (self.batcher.stats() if self.batcher is not None
                      else self.replica_set.queue_stats()),
            "streams": self.sessions.status(),
            "decode": decode,
        }
        if self.replica_set is not None:
            st["replicas"] = self.replica_set.stats()
        if self.autoscaler is not None:
            st["autoscaler"] = self.autoscaler.status()
        return st


# The most recent started server, so the training UI's /serve/status route
# can show serving next to training health without holding a reference.
_ACTIVE: Optional[InferenceServer] = None
_ACTIVE_LOCK = threading.Lock()


def _set_active_server(server: Optional[InferenceServer],
                       only_if: Optional[InferenceServer] = None) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if only_if is not None and _ACTIVE is not only_if:
            return
        _ACTIVE = server


def active_server() -> Optional[InferenceServer]:
    with _ACTIVE_LOCK:
        return _ACTIVE


def serve_status() -> dict:
    """Registry + queue status for whatever is serving right now (the
    training UI's /serve/status payload; registry-only when no
    InferenceServer has started)."""
    srv = active_server()
    if srv is not None:
        return srv.status()
    return {**global_model_registry().status(), "queue": None, "streams": {}}


def serve_slo() -> dict:
    """Current SLO burn-rate evaluation (the UI's /serve/slo payload):
    the live server's engine when one is running — so alert state and
    cooldowns are the real ones — else a fresh evaluation over the same
    process-global histograms."""
    srv = active_server()
    if srv is not None:
        return {"slo": srv.slo.evaluate()}
    return {"slo": SLOEngine().evaluate()}
