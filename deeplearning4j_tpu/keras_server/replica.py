"""Multi-replica serving: N pinned programs behind a least-queue router.

One ``PredictFn`` is one compiled program on one placement — a single
device, or a sharded mesh slice (``nn/inference.py``). A :class:`ReplicaSet`
runs N of them **independently**: each replica owns its own
``ModelRegistry`` (its own pinned snapshots), its own
``AdmissionController`` and its own ``MicroBatcher`` dispatcher thread, so
replicas share no lock on the hot path and a wedged replica cannot stall
its siblings. The front router picks the replica with the fewest
admitted-but-unanswered requests (least-queue-depth, ties to the lowest
index) and falls through to the next on admission rejection — backpressure
(HTTP 429) happens only when EVERY replica is full.

Placement (over ``jax.devices()`` or an explicit device list):

- unsharded: replica i pins on ``devices[i % len(devices)]`` — N chips,
  N independent programs, horizontal QPS scale;
- ``sharding="dp_tp"`` (or any rule set): the device list is cut into N
  contiguous slices and each replica gets its own mesh over its slice
  (``parallel.mesh.build_mesh``), so tensor-parallel serving and replica
  scale-out compose — 8 chips = 4 replicas x 2-way-sharded params.

**Rolling hot swap.** ``register()`` upgrades one replica at a time: mark
it draining (the router stops routing to it while siblings can serve),
wait for its queue to empty, then let its registry do the PR 9
atomic-pointer swap, then undrain and move to the next replica. In-flight
requests always complete against the version they resolved at dispatch —
zero request loss across a full fleet upgrade (pinned by
tests/test_serving_replica.py) — and the fleet serves at N-1 capacity
during the roll instead of pausing.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.tracing import trace_span

from .admission import RejectedError
from .batcher import MicroBatcher
from .registry import ModelRegistry, ModelVersion, load_model_file


class Replica:
    """One serving lane: private registry + admission + dispatcher."""

    def __init__(self, index: int, *, device=None, mesh=None,
                 sharding: Optional[str] = None, max_batch: int = 32,
                 max_latency_s: float = 0.002, max_queue: int = 256,
                 metrics=None, warmup: bool = False):
        self.index = index
        self.device = device
        self.mesh = mesh
        self.sharding = sharding
        #: router-visible: a draining replica takes no NEW requests while
        #: its registry swaps versions (its queued work still completes)
        self.draining = False
        # warmup pre-builds every bucket program before each register's
        # pointer swap, so a replica joins the router compile-free
        self.registry = ModelRegistry(
            metrics=metrics,
            warmup_max_batch=max_batch if warmup else None)
        self.batcher = MicroBatcher(
            self.registry, max_batch=max_batch, max_latency_s=max_latency_s,
            max_queue=max_queue, metrics=metrics, replica=index)

    def queue_depth(self) -> int:
        """Admitted-but-unanswered requests (the router's load signal)."""
        return self.batcher.admission.pending

    def devices(self) -> list:
        if self.mesh is not None:
            return [str(d) for d in self.mesh.devices.flatten()]
        if self.device is not None:
            return [str(self.device)]
        return []


class ReplicaSet:
    """N independent replicas behind a least-queue-depth router."""

    def __init__(self, n_replicas: int, *, sharding: Optional[str] = None,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 devices=None, max_batch: int = 32,
                 max_latency_s: float = 0.002, max_queue: int = 256,
                 metrics=None, drain_timeout_s: float = 30.0,
                 warmup: bool = False):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.sharding = sharding
        self.drain_timeout_s = float(drain_timeout_s)
        m = metrics or global_registry()
        self._c_routed = m.counter(
            _n.SERVE_REPLICA_ROUTED_TOTAL,
            "requests routed per replica (least-queue-depth dispatch)")
        self._g_active_version = m.gauge(
            _n.SERVE_REPLICA_ACTIVE_VERSION,
            "1 on the (replica, model, version) series currently active")
        self._lock = threading.Lock()
        self._versions: Dict[str, List[str]] = {}
        self._routed: Dict[int, int] = {i: 0 for i in range(n_replicas)}
        self._gauge_active: Dict[tuple, str] = {}
        self._replicas = [
            Replica(i, max_batch=max_batch, max_latency_s=max_latency_s,
                    max_queue=max_queue, metrics=m, warmup=warmup,
                    **placement)
            for i, placement in enumerate(
                self._placements(n_replicas, sharding, mesh_axes, devices))]

    @staticmethod
    def _placements(n: int, sharding: Optional[str],
                    mesh_axes: Optional[Dict[str, int]],
                    devices) -> List[dict]:
        import jax
        devs = list(devices) if devices is not None else list(jax.devices())
        if sharding is None:
            # round-robin: more replicas than devices is legal (CPU scale
            # tests; oversubscribed chips are the operator's call)
            return [{"device": devs[i % len(devs)]} for i in range(n)]
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        per = len(devs) // n
        if per < 1:
            raise ValueError(
                f"{n} sharded replicas need >= {n} devices, "
                f"have {len(devs)}")
        if mesh_axes is None:
            # default slice shape: give the model axis the factor of two
            # when available — dp_tp with model=1 would be sharding theater
            model = 2 if per % 2 == 0 else 1
            mesh_axes = {"data": per // model, "model": model}
        need = 1
        for v in mesh_axes.values():
            need *= v
        if need > per:
            raise ValueError(
                f"mesh_axes {mesh_axes} needs {need} devices per replica "
                f"but only {per} are available for each of {n} replicas")
        return [{"mesh": build_mesh(mesh_axes, devices=devs[i * per:
                                                           i * per + need]),
                 "sharding": sharding} for i in range(n)]

    # ------------------------------------------------------------ registry
    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    @property
    def primary_registry(self) -> ModelRegistry:
        """Replica 0's registry — the front door's model lookup (404s,
        streaming, decode) reads this; all replicas hold the same
        (name, version) catalog after every ``register()``."""
        return self._replicas[0].registry

    def _wait_drained(self, replica: Replica) -> bool:
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            if replica.queue_depth() == 0:
                return True
            time.sleep(0.002)
        return False

    def register(self, name: str, net, version: Optional[str] = None,
                 source: str = "memory",
                 quant: Optional[str] = None) -> ModelVersion:
        """Rolling registration: pin ``net`` on every replica, one at a
        time, draining each before its atomic pointer swap.

        The version is allocated once at ReplicaSet level so all replicas
        agree on the catalog. During the roll, siblings keep serving the
        old version — a fleet-wide upgrade never drops below N-1 live
        replicas and loses zero in-flight requests.
        """
        with self._lock:
            versions = self._versions.setdefault(name, [])
            version = version or f"v{len(versions) + 1}"
            if version in versions:
                raise ValueError(
                    f"model {name!r} already has version {version!r}; "
                    "versions are immutable — register a new one")
            versions.append(version)
        first: Optional[ModelVersion] = None
        for r in self._replicas:
            # drain only when a sibling can absorb the traffic — a lone
            # replica swaps atomically under load instead of pausing
            drain = any(not o.draining for o in self._replicas if o is not r)
            r.draining = drain
            try:
                if drain:
                    self._wait_drained(r)
                mv = r.registry.register(
                    name, net, version=version, source=source, quant=quant,
                    sharding=r.sharding, mesh=r.mesh, device=r.device,
                    replica=r.index)
            finally:
                r.draining = False
            prev = self._gauge_active.get((r.index, name))
            if prev is not None:
                self._g_active_version.labels(
                    replica=str(r.index), model=name, version=prev).set(0)
            self._g_active_version.labels(
                replica=str(r.index), model=name, version=version).set(1)
            self._gauge_active[(r.index, name)] = version
            if first is None:
                first = mv
        return first

    def load(self, name: str, path: str, version: Optional[str] = None,
             quant: Optional[str] = None) -> ModelVersion:
        """Load a model file once and roll it onto every replica."""
        return self.register(name, load_model_file(path), version=version,
                             source=path, quant=quant)

    # -------------------------------------------------------------- router
    def submit(self, model: str, x) -> Future:
        """Route one request to the least-loaded non-draining replica,
        falling through to the next on admission rejection; raises the
        last :class:`RejectedError` only when every replica refused."""
        candidates = [r for r in self._replicas if not r.draining] \
            or list(self._replicas)
        last: Optional[RejectedError] = None
        with trace_span("replica.route", model=model) as sp:
            tried = 0
            for r in sorted(candidates, key=lambda r: (r.queue_depth(),
                                                       r.index)):
                tried += 1
                try:
                    fut = r.batcher.submit(model, x)
                except RejectedError as e:
                    last = e
                    continue
                self._c_routed.labels(replica=str(r.index)).inc()
                sp.set_attr(replica=r.index, tried=tried)
                with self._lock:
                    self._routed[r.index] += 1
                return fut
            sp.set_status("rejected")
            sp.set_attr(tried=tried)
            assert last is not None
            raise last

    # ------------------------------------------------------------- control
    def queue_stats(self) -> dict:
        """Aggregate stats in the single-batcher shape (the /serve/status
        "queue" block keeps its schema in replica mode)."""
        per = [r.batcher.stats() for r in self._replicas]
        dispatches = sum(s["dispatches"] for s in per)
        return {
            "queue_depth": sum(s["queue_depth"] for s in per),
            "pending": sum(s["pending"] for s in per),
            "max_queue": sum(s["max_queue"] for s in per),
            "rejected": sum(s["rejected"] for s in per),
            "dispatches": dispatches,
            "mean_occupancy": (
                sum(s["mean_occupancy"] * s["dispatches"] for s in per)
                / dispatches if dispatches else 0.0),
            "bucket_count": sum(s["bucket_count"] for s in per),
            "max_batch": per[0]["max_batch"],
            "max_latency_s": per[0]["max_latency_s"],
            "replicas": len(per),
        }

    def stats(self) -> dict:
        """Per-replica detail for /serve/status's "replicas" block."""
        with self._lock:
            routed = dict(self._routed)
        reps = []
        for r in self._replicas:
            s = r.batcher.stats()
            reps.append({
                "replica": r.index,
                "draining": r.draining,
                "queue_depth": r.queue_depth(),
                "routed": routed[r.index],
                "dispatches": s["dispatches"],
                "mean_occupancy": s["mean_occupancy"],
                "bucket_count": s["bucket_count"],
                "rejected": s["rejected"],
                "sharding": r.sharding,
                "devices": r.devices(),
                "active": {name: r.registry.active(name).version
                           for name in r.registry.names()},
            })
        return {"n_replicas": len(self._replicas),
                "sharding": self.sharding, "replicas": reps}

    def close(self, timeout_s: float = 5.0) -> None:
        for r in self._replicas:
            r.batcher.close(timeout_s)
