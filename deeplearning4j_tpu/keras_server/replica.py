"""Multi-replica serving: N pinned programs behind a least-queue router.

One ``PredictFn`` is one compiled program on one placement — a single
device, or a sharded mesh slice (``nn/inference.py``). A :class:`ReplicaSet`
runs N of them **independently**: each replica owns its own
``ModelRegistry`` (its own pinned snapshots), its own
``AdmissionController`` and its own ``MicroBatcher`` dispatcher thread, so
replicas share no lock on the hot path and a wedged replica cannot stall
its siblings. The front router picks the replica with the fewest
admitted-but-unanswered requests (least-queue-depth, ties to the lowest
index) and falls through to the next on admission rejection — backpressure
(HTTP 429) happens only when EVERY replica is full.

Placement (over ``jax.devices()`` or an explicit device list):

- unsharded: replica i pins on ``devices[i % len(devices)]`` — N chips,
  N independent programs, horizontal QPS scale;
- ``sharding="dp_tp"`` (or any rule set): the device list is cut into N
  contiguous slices and each replica gets its own mesh over its slice
  (``parallel.mesh.build_mesh``), so tensor-parallel serving and replica
  scale-out compose — 8 chips = 4 replicas x 2-way-sharded params.

**Rolling hot swap.** ``register()`` upgrades one replica at a time: mark
it draining (the router stops routing to it while siblings can serve),
wait for its queue to empty, then let its registry do the PR 9
atomic-pointer swap, then undrain and move to the next replica. In-flight
requests always complete against the version they resolved at dispatch —
zero request loss across a full fleet upgrade (pinned by
tests/test_serving_replica.py) — and the fleet serves at N-1 capacity
during the roll instead of pausing.

**Elastic fleet.** ``add_replica()`` / ``remove_replica()`` mutate the set
at runtime (the autoscaler's actuators; see ``autoscaler.py``). Replica
indices are allocated monotonically and never reused, so router math,
per-replica metric series and ``~r<i>`` program names stay stable while
the set churns. A new replica spins up through the warm path: it
pre-registers the fleet's whole model catalog (every bucket program built,
warm-hitting the PR 15 compile cache because the executable fingerprint
sheds the ``~r<i>`` decoration) and only THEN becomes visible to the
router. Removal is the drain-without-loss idiom: mark draining, wait for
the queue to empty, unlink, then close — no in-flight request is lost
across a scale-down. With a ``cloud.MembershipOracle`` attached, each
replica holds a lease and the router skips any replica whose ``(member,
epoch)`` no longer validates — a zombie replica is fenced out of the
dispatch path exactly like a zombie PS worker.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability import names as _n
from deeplearning4j_tpu.observability.federation import (
    global_federation as _global_federation,
)
from deeplearning4j_tpu.observability.metrics import global_registry
from deeplearning4j_tpu.observability.tracing import trace_span

from .admission import RejectedError
from .batcher import MicroBatcher
from .registry import ModelRegistry, ModelVersion, load_model_file


class Replica:
    """One serving lane: private registry + admission + dispatcher."""

    def __init__(self, index: int, *, device=None, mesh=None,
                 sharding: Optional[str] = None, max_batch: int = 32,
                 max_latency_s: float = 0.002, max_queue: int = 256,
                 metrics=None, warmup: bool = False):
        self.index = index
        self.device = device
        self.mesh = mesh
        self.sharding = sharding
        #: router-visible: a draining replica takes no NEW requests while
        #: its registry swaps versions (its queued work still completes)
        self.draining = False
        #: cloud.WorkerLease when the set runs with a MembershipOracle —
        #: the router validates it per dispatch (zombie fencing)
        self.lease = None
        # warmup pre-builds every bucket program before each register's
        # pointer swap, so a replica joins the router compile-free
        self.registry = ModelRegistry(
            metrics=metrics,
            warmup_max_batch=max_batch if warmup else None)
        self.batcher = MicroBatcher(
            self.registry, max_batch=max_batch, max_latency_s=max_latency_s,
            max_queue=max_queue, metrics=metrics, replica=index)

    def queue_depth(self) -> int:
        """Admitted-but-unanswered requests (the router's load signal)."""
        return self.batcher.admission.pending

    def devices(self) -> list:
        if self.mesh is not None:
            return [str(d) for d in self.mesh.devices.flatten()]
        if self.device is not None:
            return [str(self.device)]
        return []


class ReplicaSet:
    """N independent replicas behind a least-queue-depth router."""

    def __init__(self, n_replicas: int, *, sharding: Optional[str] = None,
                 mesh_axes: Optional[Dict[str, int]] = None,
                 devices=None, max_batch: int = 32,
                 max_latency_s: float = 0.002, max_queue: int = 256,
                 metrics=None, drain_timeout_s: float = 30.0,
                 warmup: bool = False, membership=None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.sharding = sharding
        self.drain_timeout_s = float(drain_timeout_s)
        m = metrics or global_registry()
        self._m = m
        self._max_batch = max_batch
        self._max_latency_s = max_latency_s
        self._max_queue = max_queue
        self._warmup = warmup
        self._mesh_axes = mesh_axes
        self._devices = list(devices) if devices is not None else None
        self._membership = membership
        self._c_routed = m.counter(
            _n.SERVE_REPLICA_ROUTED_TOTAL,
            "requests routed per replica (least-queue-depth dispatch)")
        self._g_active_version = m.gauge(
            _n.SERVE_REPLICA_ACTIVE_VERSION,
            "1 on the (replica, model, version) series currently active")
        self._g_fleet = m.gauge(
            _n.SERVE_FLEET_SIZE, "live serving replicas in the set")
        self._c_scale = m.counter(
            _n.SERVE_SCALE_EVENTS_TOTAL,
            "fleet size changes, by direction (out/in) and reason")
        self._lock = threading.Lock()
        # serializes fleet mutations (register roll, add/remove) against
        # each other so a replica added mid-roll can't miss a version;
        # the router's submit() never takes it
        self._mutate_lock = threading.RLock()
        self._versions: Dict[str, List[str]] = {}
        #: name -> (version, net, source, quant) of the ACTIVE version —
        #: what a newly added replica must pre-register before joining
        self._catalog: Dict[str, tuple] = {}
        self._routed: Dict[int, int] = {i: 0 for i in range(n_replicas)}
        self._gauge_active: Dict[tuple, str] = {}
        #: monotonic index allocator — indices are never reused, so metric
        #: series and program names stay unambiguous across churn
        self._next_index = n_replicas
        #: guarded-by: _lock
        self._replicas = [
            Replica(i, max_batch=max_batch, max_latency_s=max_latency_s,
                    max_queue=max_queue, metrics=m, warmup=warmup,
                    **self._placement_for(i, n_total=n_replicas))
            for i in range(n_replicas)]
        if membership is not None:
            for r in self._replicas:
                r.lease = membership.register(
                    shard=r.index, worker=f"replica-{r.index}")
                self._fed_note(r)
        self._g_fleet.set(len(self._replicas))

    def _placement_for(self, i: int, n_total: Optional[int] = None) -> dict:
        """Placement for replica index ``i``. ``n_total`` sizes the mesh
        slices at construction; afterwards the slice width is fixed, so a
        sharded scale-out only succeeds while unclaimed slices remain."""
        import jax
        devs = list(self._devices) if self._devices is not None \
            else list(jax.devices())
        if self.sharding is None:
            # round-robin: more replicas than devices is legal (CPU scale
            # tests; oversubscribed chips are the operator's call)
            return {"device": devs[i % len(devs)]}
        from deeplearning4j_tpu.parallel.mesh import build_mesh
        if n_total is not None:
            per = len(devs) // n_total
            if per < 1:
                raise ValueError(
                    f"{n_total} sharded replicas need >= {n_total} devices, "
                    f"have {len(devs)}")
            if self._mesh_axes is None:
                # default slice shape: give the model axis the factor of two
                # when available — dp_tp with model=1 would be sharding
                # theater
                model = 2 if per % 2 == 0 else 1
                self._mesh_axes = {"data": per // model, "model": model}
            self._slice_per = per
        per = self._slice_per
        need = 1
        for v in self._mesh_axes.values():
            need *= v
        if need > per:
            raise ValueError(
                f"mesh_axes {self._mesh_axes} needs {need} devices per "
                f"replica but only {per} are available for each replica")
        if i * per + need > len(devs):
            raise ValueError(
                f"no free device slice for sharded replica {i}: "
                f"{len(devs)} devices at {per} per replica")
        return {"mesh": build_mesh(self._mesh_axes,
                                   devices=devs[i * per: i * per + need]),
                "sharding": self.sharding}

    # ------------------------------------------------------------ registry
    @property
    def n_replicas(self) -> int:
        # remove_replica() rebinds the list under _lock; an unlocked len()
        # here could see the pre-swap list arbitrarily late
        with self._lock:
            return len(self._replicas)

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    @property
    def primary_registry(self) -> ModelRegistry:
        """Replica 0's registry — the front door's model lookup (404s,
        streaming, decode) reads this; all replicas hold the same
        (name, version) catalog after every ``register()``. The primary
        replica is pinned: ``remove_replica`` never takes it."""
        with self._lock:
            return self._replicas[0].registry

    def _wait_drained(self, replica: Replica) -> bool:
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            if replica.queue_depth() == 0:
                return True
            time.sleep(0.002)
        return False

    def register(self, name: str, net, version: Optional[str] = None,
                 source: str = "memory",
                 quant: Optional[str] = None) -> ModelVersion:
        """Rolling registration: pin ``net`` on every replica, one at a
        time, draining each before its atomic pointer swap.

        The version is allocated once at ReplicaSet level so all replicas
        agree on the catalog. During the roll, siblings keep serving the
        old version — a fleet-wide upgrade never drops below N-1 live
        replicas and loses zero in-flight requests.
        """
        with self._mutate_lock:
            with self._lock:
                versions = self._versions.setdefault(name, [])
                version = version or f"v{len(versions) + 1}"
                if version in versions:
                    raise ValueError(
                        f"model {name!r} already has version {version!r}; "
                        "versions are immutable — register a new one")
                versions.append(version)
                fleet = list(self._replicas)
            first: Optional[ModelVersion] = None
            for r in fleet:
                # drain only when a sibling can absorb the traffic — a lone
                # replica swaps atomically under load instead of pausing
                drain = any(not o.draining for o in fleet if o is not r)
                r.draining = drain
                try:
                    if drain:
                        # lint: blocking-under-lock-ok (drain-before-swap holds the cold _mutate_lock by design; the router path (submit) only ever takes _lock)
                        self._wait_drained(r)
                    mv = self._register_on(r, name, net, version, source,
                                           quant)
                finally:
                    r.draining = False
                if first is None:
                    first = mv
            with self._lock:
                self._catalog[name] = (version, net, source, quant)
            return first

    #: requires-lock: _mutate_lock
    def _register_on(self, r: Replica, name: str, net, version: str,
                     source: str, quant: Optional[str]) -> ModelVersion:
        """Pin one (model, version) on one replica and flip its
        active-version gauge series (register()/add_replica() call this
        inside the mutation critical section)."""
        mv = r.registry.register(
            name, net, version=version, source=source, quant=quant,
            sharding=r.sharding, mesh=r.mesh, device=r.device,
            replica=r.index)
        prev = self._gauge_active.get((r.index, name))
        if prev is not None:
            self._g_active_version.labels(
                replica=str(r.index), model=name, version=prev).set(0)
        self._g_active_version.labels(
            replica=str(r.index), model=name, version=version).set(1)
        self._gauge_active[(r.index, name)] = version
        return mv

    def load(self, name: str, path: str, version: Optional[str] = None,
             quant: Optional[str] = None) -> ModelVersion:
        """Load a model file once and roll it onto every replica."""
        return self.register(name, load_model_file(path), version=version,
                             source=path, quant=quant)

    # ------------------------------------------------------- fleet scaling
    def add_replica(self, reason: str = "manual") -> Replica:
        """Grow the fleet by one, atomically from the router's view.

        The new replica is built on the next free placement with warmup
        forced on, then pre-registers the active version of every model in
        the catalog — every bucket program is compiled (warm-hitting the
        persistent executable cache, whose fingerprint ignores the
        ``~r<i>`` replica decoration) BEFORE the replica is appended to the
        routable list. The router never sees a cold replica.
        """
        with self._mutate_lock:
            with self._lock:
                idx = self._next_index
                self._next_index += 1
                catalog = dict(self._catalog)
            r = Replica(idx, max_batch=self._max_batch,
                        max_latency_s=self._max_latency_s,
                        max_queue=self._max_queue, metrics=self._m,
                        warmup=True, **self._placement_for(idx))
            for name, (version, net, source, quant) in catalog.items():
                self._register_on(r, name, net, version, source, quant)
            if self._membership is not None:
                r.lease = self._membership.register(
                    shard=idx, worker=f"replica-{idx}")
                self._fed_note(r)
            with self._lock:
                self._replicas.append(r)
                self._routed[idx] = 0
                self._g_fleet.set(len(self._replicas))
            self._c_scale.labels(direction="out", reason=reason).inc()
            return r

    def remove_replica(self, index: Optional[int] = None,
                       reason: str = "manual") -> bool:
        """Shrink the fleet by one with the drain-without-loss idiom:
        mark draining (the router stops sending new work), wait for the
        queue to empty, unlink from the routable list, then close the
        dispatcher — every admitted request completes.

        Defaults to the highest-index replica. The primary replica
        (``_replicas[0]``, whose registry is the front door) is pinned and
        cannot be removed; the last replica cannot be removed either.
        """
        with self._mutate_lock:
            with self._lock:
                if len(self._replicas) <= 1:
                    raise ValueError("cannot remove the last replica")
                primary = self._replicas[0]
                if index is None:
                    r = max(self._replicas[1:], key=lambda o: o.index)
                else:
                    found = [o for o in self._replicas
                             if o.index == int(index)]
                    if not found:
                        return False
                    r = found[0]
                    if r is primary:
                        raise ValueError(
                            "cannot remove the primary replica (its "
                            "registry is the front door)")
            r.draining = True
            # lint: blocking-under-lock-ok (scale-in drain holds the cold _mutate_lock by design; the router path (submit) only ever takes _lock)
            self._wait_drained(r)
            with self._lock:
                self._replicas = [o for o in self._replicas if o is not r]
                self._g_fleet.set(len(self._replicas))
            # close() drains anything that slipped in before the unlink —
            # admitted work still completes, new work can no longer arrive
            # lint: blocking-under-lock-ok (dispatcher join during scale-in holds the cold _mutate_lock; mutations serialize, the router never waits on it)
            r.batcher.close(self.drain_timeout_s)
            if self._membership is not None and r.lease is not None:
                self._membership.deregister(
                    r.lease.member, r.lease.epoch, reason=reason)
                self._fed_retire(r)
            for name in r.registry.names():
                prev = self._gauge_active.pop((r.index, name), None)
                if prev is not None:
                    self._g_active_version.labels(
                        replica=str(r.index), model=name,
                        version=prev).set(0)
            self._c_scale.labels(direction="in", reason=reason).inc()
            return True

    def _fed_note(self, r: Replica) -> None:
        """Put the replica's lease on the federation roster (when one is
        installed): the fleet view labels its series ``replica=<name>`` and
        /fleet/status lists it with its fencing epoch."""
        fed = _global_federation()
        if fed is not None and r.lease is not None:
            fed.note_member(name=r.lease.name, epoch=r.lease.epoch,
                            role="replica", member=r.lease.member)

    def _fed_retire(self, r: Replica) -> None:
        fed = _global_federation()
        if fed is not None and r.lease is not None:
            fed.retire_member(r.lease.name, r.lease.epoch)

    def heartbeat(self) -> None:
        """Renew the lease of every in-set replica (they share our
        process: being in the routable list is liveness). Evicted or
        superseded leases stay dead — heartbeat cannot resurrect them."""
        if self._membership is None:
            return
        for r in self.replicas:
            if r.lease is not None:
                self._membership.heartbeat(r.lease.member, r.lease.epoch)

    def _lease_ok(self, r: Replica) -> bool:
        if self._membership is None or r.lease is None:
            return True
        return self._membership.validate(r.lease.member, r.lease.epoch)

    def fenced_replicas(self) -> List[Replica]:
        """Replicas whose lease no longer validates (the autoscaler's
        zombie sweep reads this to evict-and-replace)."""
        return [r for r in self.replicas if not self._lease_ok(r)]

    # -------------------------------------------------------------- router
    def submit(self, model: str, x, *, priority: str = "high",
               tenant: str = "-") -> Future:
        """Route one request to the least-loaded non-draining replica,
        falling through to the next on admission rejection; raises the
        last :class:`RejectedError` only when every replica refused.
        Replicas with a lapsed membership lease are fenced out entirely."""
        with self._lock:
            fleet = list(self._replicas)
        live = [r for r in fleet if self._lease_ok(r)] or fleet
        candidates = [r for r in live if not r.draining] or live
        last: Optional[RejectedError] = None
        with trace_span("replica.route", model=model) as sp:
            tried = 0
            for r in sorted(candidates, key=lambda r: (r.queue_depth(),
                                                       r.index)):
                tried += 1
                try:
                    fut = r.batcher.submit(model, x, priority=priority,
                                           tenant=tenant)
                except RejectedError as e:
                    last = e
                    continue
                self._c_routed.labels(replica=str(r.index)).inc()
                sp.set_attr(replica=r.index, tried=tried)
                with self._lock:
                    self._routed[r.index] = \
                        self._routed.get(r.index, 0) + 1
                return fut
            sp.set_status("rejected")
            sp.set_attr(tried=tried)
            assert last is not None
            raise last

    # ------------------------------------------------------------- control
    def queue_stats(self) -> dict:
        """Aggregate stats in the single-batcher shape (the /serve/status
        "queue" block keeps its schema in replica mode)."""
        per = [r.batcher.stats() for r in self.replicas]
        dispatches = sum(s["dispatches"] for s in per)
        return {
            "queue_depth": sum(s["queue_depth"] for s in per),
            "pending": sum(s["pending"] for s in per),
            "max_queue": sum(s["max_queue"] for s in per),
            "rejected": sum(s["rejected"] for s in per),
            "dispatches": dispatches,
            "mean_occupancy": (
                sum(s["mean_occupancy"] * s["dispatches"] for s in per)
                / dispatches if dispatches else 0.0),
            "bucket_count": sum(s["bucket_count"] for s in per),
            "max_batch": per[0]["max_batch"],
            "max_latency_s": per[0]["max_latency_s"],
            "replicas": len(per),
        }

    def stats(self) -> dict:
        """Per-replica detail for /serve/status's "replicas" block."""
        with self._lock:
            routed = dict(self._routed)
            fleet = list(self._replicas)
        reps = []
        for r in fleet:
            s = r.batcher.stats()
            reps.append({
                "replica": r.index,
                "draining": r.draining,
                "fenced": not self._lease_ok(r),
                "queue_depth": r.queue_depth(),
                "routed": routed.get(r.index, 0),
                "dispatches": s["dispatches"],
                "mean_occupancy": s["mean_occupancy"],
                "bucket_count": s["bucket_count"],
                "rejected": s["rejected"],
                "sharding": r.sharding,
                "devices": r.devices(),
                "active": {name: r.registry.active(name).version
                           for name in r.registry.names()},
            })
        return {"n_replicas": len(fleet),
                "sharding": self.sharding, "replicas": reps}

    def close(self, timeout_s: float = 5.0) -> None:
        for r in self.replicas:
            r.batcher.close(timeout_s)
