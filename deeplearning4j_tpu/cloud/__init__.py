"""Cloud storage + provisioning equivalents.

Reference: deeplearning4j-aws (SURVEY.md §2.4) — S3Uploader/S3Downloader for
artifact transfer and Ec2BoxCreator for box provisioning. The TPU-native
equivalents keep the same SPI shapes: a ``StorageProvider`` with a local-
filesystem backend (always available; object-store backends plug in behind
the same interface but are gated — this image has zero egress), and a
``TpuProvisioner`` that renders the accelerator-pool request the way
Ec2BoxCreator rendered EC2 run-instance requests.
"""
from __future__ import annotations

import dataclasses
import shutil
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import (
    ELASTIC_JOINS_TOTAL, ELASTIC_LEASE_EXPIRIES_TOTAL, ELASTIC_LIVE_WORKERS,
)

_live_workers = _obs_registry().gauge(
    ELASTIC_LIVE_WORKERS, "workers holding a live membership lease").labels()
_lease_expiries = _obs_registry().counter(
    ELASTIC_LEASE_EXPIRIES_TOTAL,
    "membership leases declared dead after missing heartbeats").labels()
_joins = _obs_registry().counter(
    ELASTIC_JOINS_TOTAL, "worker registrations with the membership "
                         "oracle").labels()


class StorageProvider:
    """Artifact up/download SPI (reference S3Uploader/S3Downloader)."""

    def upload(self, local_path: str, remote_path: str) -> str:
        raise NotImplementedError

    def download(self, remote_path: str, local_path: str) -> str:
        raise NotImplementedError

    def list(self, remote_prefix: str) -> List[str]:
        raise NotImplementedError


class LocalFileSystemProvider(StorageProvider):
    """Filesystem-backed store (the always-available backend; doubles as the
    mount-point backend for NFS/GCS-FUSE style deployments)."""

    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _resolve(self, remote_path: str) -> Path:
        p = (self.root / remote_path.lstrip("/")).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"remote path escapes store root: {remote_path}")
        return p

    def upload(self, local_path: str, remote_path: str) -> str:
        dst = self._resolve(remote_path)
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local_path, dst)
        return str(dst)

    def download(self, remote_path: str, local_path: str) -> str:
        src = self._resolve(remote_path)
        Path(local_path).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, local_path)
        return local_path

    def list(self, remote_prefix: str = "") -> List[str]:
        base = self._resolve(remote_prefix) if remote_prefix else self.root
        if not base.exists():
            return []
        return sorted(str(p.relative_to(self.root))
                      for p in base.rglob("*") if p.is_file())


class HttpStorageProvider(StorageProvider):
    """Object-store backend over plain HTTP PUT/GET/list — bytes move
    through a real socket, the role reference S3Uploader.java fills (S3's
    REST surface is exactly this shape: PUT object, GET object, GET
    ?prefix= listing). Point it at any S3-compatible/HTTP object endpoint;
    ``serve_storage()`` below stands up a loopback server so the contract
    is exercised end-to-end without egress (tests/test_cloud_streaming.py).
    """

    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _request(self, method: str, path: str, data=None,
                 headers: Optional[dict] = None):
        import urllib.request

        req = urllib.request.Request(
            f"{self.base_url}/{path.lstrip('/')}", data=data, method=method,
            headers=dict(headers or {}))
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def upload(self, local_path: str, remote_path: str) -> str:
        # stream from disk: urllib sends a file object chunk-wise when
        # Content-Length is set, so memory stays O(buffer), not O(artifact)
        size = Path(local_path).stat().st_size
        with open(local_path, "rb") as f:
            with self._request("PUT", remote_path, data=f,
                               headers={"Content-Length": str(size)}) as resp:
                if resp.status not in (200, 201, 204):
                    raise IOError(f"upload failed: HTTP {resp.status}")
        return f"{self.base_url}/{remote_path.lstrip('/')}"

    def download(self, remote_path: str, local_path: str) -> str:
        Path(local_path).parent.mkdir(parents=True, exist_ok=True)
        with self._request("GET", remote_path) as resp:
            with open(local_path, "wb") as f:
                shutil.copyfileobj(resp, f)
        return local_path

    def list(self, remote_prefix: str = "") -> List[str]:
        import urllib.parse

        q = urllib.parse.urlencode({"prefix": remote_prefix})
        with self._request("GET", f"?{q}") as resp:
            body = resp.read().decode("utf-8")
        return [line for line in body.splitlines() if line]


def serve_storage(root: str, host: str = "127.0.0.1", port: int = 0,
                  token: Optional[str] = None):
    """Loopback artifact server backing HttpStorageProvider: PUT stores,
    GET serves, ``GET /?prefix=`` lists. Returns (server, base_url); run
    ``server.serve_forever()`` on a thread and ``server.shutdown()`` when
    done. Storage is a LocalFileSystemProvider root, so the path-escape
    guard applies to remote names too."""
    import urllib.parse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    store = LocalFileSystemProvider(root)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # tests stay quiet
            pass

        def _authed(self) -> bool:
            if token is None:
                return True
            if self.headers.get("Authorization") == f"Bearer {token}":
                return True
            self.send_response(401)
            self.end_headers()
            return False

        def do_PUT(self):
            if not self._authed():
                return
            try:
                dst = store._resolve(urllib.parse.unquote(self.path))
            except ValueError:
                self.send_response(400)
                self.end_headers()
                return
            if "Content-Length" not in self.headers:
                self.send_response(411)  # length required — no silent empties
                self.end_headers()
                return
            n = int(self.headers["Content-Length"])
            dst.parent.mkdir(parents=True, exist_ok=True)
            # stream to disk in chunks (multi-GB checkpoints must not
            # materialize in handler memory)
            with open(dst, "wb") as f:
                remaining = n
                while remaining > 0:
                    chunk = self.rfile.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    f.write(chunk)
                    remaining -= len(chunk)
            if remaining:
                # truncated body: never acknowledge a partial artifact
                dst.unlink(missing_ok=True)
                self.send_response(400)
                self.end_headers()
                return
            self.send_response(201)
            self.end_headers()

        def do_GET(self):
            if not self._authed():
                return
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path in ("", "/"):
                prefix = urllib.parse.parse_qs(parsed.query).get(
                    "prefix", [""])[0]
                try:
                    names = store.list(prefix)
                except ValueError:  # escaping prefix -> clean 400, like PUT
                    self.send_response(400)
                    self.end_headers()
                    return
                body = "\n".join(names).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                src = store._resolve(urllib.parse.unquote(parsed.path))
            except ValueError:
                self.send_response(400)
                self.end_headers()
                return
            if not src.is_file():
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(src.stat().st_size))
            self.end_headers()
            with open(src, "rb") as f:
                shutil.copyfileobj(f, self.wfile)

    server = ThreadingHTTPServer((host, port), Handler)
    return server, f"http://{host}:{server.server_address[1]}"


class S3Provider(StorageProvider):
    """Gated object-store backend (reference S3Uploader/S3Downloader). This
    image has no egress and no boto3; constructing raises with instructions
    rather than failing at first use."""

    def __init__(self, bucket: str):
        raise RuntimeError(
            "S3/object-store transfer requires network egress and an S3 "
            "client, neither of which is available in this environment. Use "
            "LocalFileSystemProvider against a mounted path, or deploy with "
            f"an object-store client to reach bucket {bucket!r}.")


@dataclasses.dataclass
class TpuProvisioner:
    """Accelerator-pool request builder (reference aws Ec2BoxCreator renders
    EC2 RunInstances; the TPU equivalent renders a queued-resource request).
    ``render()`` produces the request dict a deployment tool would submit."""

    accelerator_type: str = "v5litepod-16"
    runtime_version: str = "tpu-ubuntu2204-base"
    zone: str = "us-central1-a"
    num_slices: int = 1
    preemptible: bool = False

    def render(self, name: str) -> dict:
        return {
            "name": name,
            "accelerator_type": self.accelerator_type,
            "runtime_version": self.runtime_version,
            "zone": self.zone,
            "num_slices": self.num_slices,
            "spot": self.preemptible,
        }


@dataclasses.dataclass
class WorkerLease:
    """One worker's membership record: a fencing ``epoch`` (globally
    monotonic per registration) plus a heartbeat-renewed deadline."""

    member: int
    epoch: int
    shard: int
    name: str
    deadline: float
    alive: bool = True
    reason: Optional[str] = None   # why the lease ended, once it has


@dataclasses.dataclass
class MembershipOracle(TpuProvisioner):
    """TpuProvisioner grown into the elastic-training membership authority.

    Provisioning describes the pool a deployment *requests*; the oracle
    tracks the pool that actually *showed up*: workers ``register`` (getting
    a member id + fencing epoch + lease), renew via ``heartbeat``, and leave
    via ``deregister``. A lease that is not renewed within
    ``lease_timeout_s`` is declared dead — liveness is decided server-side,
    never by the worker's own opinion of itself.

    The epoch is the fence: every registration draws a fresh, globally
    monotonic epoch, and the parameter server (``ParameterServer(...,
    membership=oracle)``) rejects pushes carrying a dead or superseded
    ``(member, epoch)``. A zombie — a preempted worker resumed after its
    lease lapsed and its shard was handed off — can still talk, but its
    pushes no longer land. Pushes deliberately do NOT renew the lease: only
    heartbeats prove liveness, so a zombie busy-pushing stays dead.

    ``clock`` is injectable (default ``time.monotonic``) so lease math is
    unit-testable with a fake clock.

    ``role`` names what kind of member the oracle fences — ``"worker"``
    for training (the default, and the historical behaviour) or
    ``"replica"`` for the serving fleet (``keras_server/autoscaler.py``).
    It only affects default member names and flight-recorder event names
    (``{role}_join`` / ``{role}_leave`` / ``{role}_lost``); the lease and
    epoch fencing semantics are identical for both.
    """

    lease_timeout_s: float = 15.0
    clock: Callable[[], float] = time.monotonic
    role: str = "worker"

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._members: Dict[int, WorkerLease] = {}
        self._epoch = 0
        self.lease_expiries = 0
        self.joins = 0

    # ----------------------------------------------------------- membership
    def register(self, shard: int, worker: str = "") -> WorkerLease:
        with self._lock:
            self._epoch += 1
            lease = WorkerLease(
                member=self._epoch, epoch=self._epoch, shard=int(shard),
                name=worker or f"{self.role}-{self._epoch}",
                deadline=self.clock() + self.lease_timeout_s)
            self._members[lease.member] = lease
            self.joins += 1
            _joins.inc()
            self._update_gauge_locked()
        _flight_recorder().record(
            f"{self.role}_join", member=lease.member, epoch=lease.epoch,
            shard=lease.shard, worker=lease.name)
        return lease

    def heartbeat(self, member: int, epoch: int) -> bool:
        """Renew ``member``'s lease; False means the lease is gone (dead,
        superseded, or lapsed) and the worker must stop pushing."""
        with self._lock:
            lease = self._members.get(int(member))
            if lease is None or lease.epoch != int(epoch):
                return False
            if not lease.alive:
                return False
            if self.clock() > lease.deadline:
                self._expire_locked(lease, reason="lease-lapsed")
                return False
            lease.deadline = self.clock() + self.lease_timeout_s
            return True

    def deregister(self, member: int, epoch: int,
                   reason: str = "done") -> bool:
        """Graceful leave: the lease ends without counting as an expiry."""
        with self._lock:
            lease = self._members.get(int(member))
            if lease is None or lease.epoch != int(epoch) or not lease.alive:
                return False
            lease.alive = False
            lease.reason = reason
            self._update_gauge_locked()
        _flight_recorder().record(
            f"{self.role}_leave", member=lease.member, shard=lease.shard,
            reason=reason)
        return True

    def validate(self, member: int, epoch: int) -> bool:
        """Server-side fencing check at push time: the ``(member, epoch)``
        pair must name a live, unlapsed lease. Lazily expires a lapsed lease
        so fencing holds even between ``expire()`` sweeps; does NOT renew."""
        with self._lock:
            lease = self._members.get(int(member))
            if lease is None or lease.epoch != int(epoch):
                return False
            if not lease.alive:
                return False
            if self.clock() > lease.deadline:
                self._expire_locked(lease, reason="lease-lapsed")
                return False
            return True

    def expire(self, now: Optional[float] = None) -> List[WorkerLease]:
        """Sweep: declare every lapsed lease dead; returns the newly dead."""
        now = self.clock() if now is None else now
        with self._lock:
            lapsed = [l for l in self._members.values()
                      if l.alive and now > l.deadline]
            for lease in lapsed:
                self._expire_locked(lease, reason="lease-lapsed")
        return lapsed

    def evict(self, member: int, reason: str = "process-exit") -> bool:
        """Coordinator-observed death (e.g. SIGKILLed process): fence the
        lease immediately instead of waiting out the lease timeout. Not
        counted as a lease expiry — the coordinator saw the body."""
        with self._lock:
            lease = self._members.get(int(member))
            if lease is None or not lease.alive:
                return False
            lease.alive = False
            lease.reason = reason
            self._update_gauge_locked()
        _flight_recorder().record(
            f"{self.role}_lost", member=lease.member, shard=lease.shard,
            reason=reason)
        return True

    # ------------------------------------------------------------- queries
    def live_members(self) -> List[WorkerLease]:
        with self._lock:
            return [l for l in self._members.values() if l.alive]

    def live_member_for_shard(self, shard: int) -> Optional[WorkerLease]:
        with self._lock:
            live = [l for l in self._members.values()
                    if l.alive and l.shard == int(shard)]
        return max(live, key=lambda l: l.epoch) if live else None

    def member_by_name(self, name: str) -> Optional[WorkerLease]:
        with self._lock:
            named = [l for l in self._members.values() if l.name == name]
        return max(named, key=lambda l: l.epoch) if named else None

    def lease(self, member: int) -> Optional[WorkerLease]:
        with self._lock:
            return self._members.get(int(member))

    # ------------------------------------------------------------ internals
    def _expire_locked(self, lease: WorkerLease, reason: str) -> None:
        lease.alive = False
        lease.reason = reason
        self.lease_expiries += 1
        _lease_expiries.inc()
        self._update_gauge_locked()
        _flight_recorder().record(
            f"{self.role}_lost", member=lease.member, shard=lease.shard,
            reason=reason)

    def _update_gauge_locked(self) -> None:
        _live_workers.set(
            sum(1 for l in self._members.values() if l.alive))
