"""Cloud storage + provisioning equivalents.

Reference: deeplearning4j-aws (SURVEY.md §2.4) — S3Uploader/S3Downloader for
artifact transfer and Ec2BoxCreator for box provisioning. The TPU-native
equivalents keep the same SPI shapes: a ``StorageProvider`` with a local-
filesystem backend (always available; object-store backends plug in behind
the same interface but are gated — this image has zero egress), and a
``TpuProvisioner`` that renders the accelerator-pool request the way
Ec2BoxCreator rendered EC2 run-instance requests.
"""
from __future__ import annotations

import dataclasses
import shutil
from pathlib import Path
from typing import List, Optional


class StorageProvider:
    """Artifact up/download SPI (reference S3Uploader/S3Downloader)."""

    def upload(self, local_path: str, remote_path: str) -> str:
        raise NotImplementedError

    def download(self, remote_path: str, local_path: str) -> str:
        raise NotImplementedError

    def list(self, remote_prefix: str) -> List[str]:
        raise NotImplementedError


class LocalFileSystemProvider(StorageProvider):
    """Filesystem-backed store (the always-available backend; doubles as the
    mount-point backend for NFS/GCS-FUSE style deployments)."""

    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _resolve(self, remote_path: str) -> Path:
        p = (self.root / remote_path.lstrip("/")).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"remote path escapes store root: {remote_path}")
        return p

    def upload(self, local_path: str, remote_path: str) -> str:
        dst = self._resolve(remote_path)
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local_path, dst)
        return str(dst)

    def download(self, remote_path: str, local_path: str) -> str:
        src = self._resolve(remote_path)
        Path(local_path).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, local_path)
        return local_path

    def list(self, remote_prefix: str = "") -> List[str]:
        base = self._resolve(remote_prefix) if remote_prefix else self.root
        if not base.exists():
            return []
        return sorted(str(p.relative_to(self.root))
                      for p in base.rglob("*") if p.is_file())


class S3Provider(StorageProvider):
    """Gated object-store backend (reference S3Uploader/S3Downloader). This
    image has no egress and no boto3; constructing raises with instructions
    rather than failing at first use."""

    def __init__(self, bucket: str):
        raise RuntimeError(
            "S3/object-store transfer requires network egress and an S3 "
            "client, neither of which is available in this environment. Use "
            "LocalFileSystemProvider against a mounted path, or deploy with "
            f"an object-store client to reach bucket {bucket!r}.")


@dataclasses.dataclass
class TpuProvisioner:
    """Accelerator-pool request builder (reference aws Ec2BoxCreator renders
    EC2 RunInstances; the TPU equivalent renders a queued-resource request).
    ``render()`` produces the request dict a deployment tool would submit."""

    accelerator_type: str = "v5litepod-16"
    runtime_version: str = "tpu-ubuntu2204-base"
    zone: str = "us-central1-a"
    num_slices: int = 1
    preemptible: bool = False

    def render(self, name: str) -> dict:
        return {
            "name": name,
            "accelerator_type": self.accelerator_type,
            "runtime_version": self.runtime_version,
            "zone": self.zone,
            "num_slices": self.num_slices,
            "spot": self.preemptible,
        }
