"""Cloud storage + provisioning equivalents.

Reference: deeplearning4j-aws (SURVEY.md §2.4) — S3Uploader/S3Downloader for
artifact transfer and Ec2BoxCreator for box provisioning. The TPU-native
equivalents keep the same SPI shapes: a ``StorageProvider`` with a local-
filesystem backend (always available; object-store backends plug in behind
the same interface but are gated — this image has zero egress), and a
``TpuProvisioner`` that renders the accelerator-pool request the way
Ec2BoxCreator rendered EC2 run-instance requests.
"""
from __future__ import annotations

import dataclasses
import shutil
from pathlib import Path
from typing import List, Optional


class StorageProvider:
    """Artifact up/download SPI (reference S3Uploader/S3Downloader)."""

    def upload(self, local_path: str, remote_path: str) -> str:
        raise NotImplementedError

    def download(self, remote_path: str, local_path: str) -> str:
        raise NotImplementedError

    def list(self, remote_prefix: str) -> List[str]:
        raise NotImplementedError


class LocalFileSystemProvider(StorageProvider):
    """Filesystem-backed store (the always-available backend; doubles as the
    mount-point backend for NFS/GCS-FUSE style deployments)."""

    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _resolve(self, remote_path: str) -> Path:
        p = (self.root / remote_path.lstrip("/")).resolve()
        if not p.is_relative_to(self.root.resolve()):
            raise ValueError(f"remote path escapes store root: {remote_path}")
        return p

    def upload(self, local_path: str, remote_path: str) -> str:
        dst = self._resolve(remote_path)
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local_path, dst)
        return str(dst)

    def download(self, remote_path: str, local_path: str) -> str:
        src = self._resolve(remote_path)
        Path(local_path).parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, local_path)
        return local_path

    def list(self, remote_prefix: str = "") -> List[str]:
        base = self._resolve(remote_prefix) if remote_prefix else self.root
        if not base.exists():
            return []
        return sorted(str(p.relative_to(self.root))
                      for p in base.rglob("*") if p.is_file())


class HttpStorageProvider(StorageProvider):
    """Object-store backend over plain HTTP PUT/GET/list — bytes move
    through a real socket, the role reference S3Uploader.java fills (S3's
    REST surface is exactly this shape: PUT object, GET object, GET
    ?prefix= listing). Point it at any S3-compatible/HTTP object endpoint;
    ``serve_storage()`` below stands up a loopback server so the contract
    is exercised end-to-end without egress (tests/test_cloud_streaming.py).
    """

    def __init__(self, base_url: str, token: Optional[str] = None,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _request(self, method: str, path: str, data=None,
                 headers: Optional[dict] = None):
        import urllib.request

        req = urllib.request.Request(
            f"{self.base_url}/{path.lstrip('/')}", data=data, method=method,
            headers=dict(headers or {}))
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def upload(self, local_path: str, remote_path: str) -> str:
        # stream from disk: urllib sends a file object chunk-wise when
        # Content-Length is set, so memory stays O(buffer), not O(artifact)
        size = Path(local_path).stat().st_size
        with open(local_path, "rb") as f:
            with self._request("PUT", remote_path, data=f,
                               headers={"Content-Length": str(size)}) as resp:
                if resp.status not in (200, 201, 204):
                    raise IOError(f"upload failed: HTTP {resp.status}")
        return f"{self.base_url}/{remote_path.lstrip('/')}"

    def download(self, remote_path: str, local_path: str) -> str:
        Path(local_path).parent.mkdir(parents=True, exist_ok=True)
        with self._request("GET", remote_path) as resp:
            with open(local_path, "wb") as f:
                shutil.copyfileobj(resp, f)
        return local_path

    def list(self, remote_prefix: str = "") -> List[str]:
        import urllib.parse

        q = urllib.parse.urlencode({"prefix": remote_prefix})
        with self._request("GET", f"?{q}") as resp:
            body = resp.read().decode("utf-8")
        return [line for line in body.splitlines() if line]


def serve_storage(root: str, host: str = "127.0.0.1", port: int = 0,
                  token: Optional[str] = None):
    """Loopback artifact server backing HttpStorageProvider: PUT stores,
    GET serves, ``GET /?prefix=`` lists. Returns (server, base_url); run
    ``server.serve_forever()`` on a thread and ``server.shutdown()`` when
    done. Storage is a LocalFileSystemProvider root, so the path-escape
    guard applies to remote names too."""
    import urllib.parse
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    store = LocalFileSystemProvider(root)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # tests stay quiet
            pass

        def _authed(self) -> bool:
            if token is None:
                return True
            if self.headers.get("Authorization") == f"Bearer {token}":
                return True
            self.send_response(401)
            self.end_headers()
            return False

        def do_PUT(self):
            if not self._authed():
                return
            try:
                dst = store._resolve(urllib.parse.unquote(self.path))
            except ValueError:
                self.send_response(400)
                self.end_headers()
                return
            if "Content-Length" not in self.headers:
                self.send_response(411)  # length required — no silent empties
                self.end_headers()
                return
            n = int(self.headers["Content-Length"])
            dst.parent.mkdir(parents=True, exist_ok=True)
            # stream to disk in chunks (multi-GB checkpoints must not
            # materialize in handler memory)
            with open(dst, "wb") as f:
                remaining = n
                while remaining > 0:
                    chunk = self.rfile.read(min(1 << 20, remaining))
                    if not chunk:
                        break
                    f.write(chunk)
                    remaining -= len(chunk)
            if remaining:
                # truncated body: never acknowledge a partial artifact
                dst.unlink(missing_ok=True)
                self.send_response(400)
                self.end_headers()
                return
            self.send_response(201)
            self.end_headers()

        def do_GET(self):
            if not self._authed():
                return
            parsed = urllib.parse.urlsplit(self.path)
            if parsed.path in ("", "/"):
                prefix = urllib.parse.parse_qs(parsed.query).get(
                    "prefix", [""])[0]
                try:
                    names = store.list(prefix)
                except ValueError:  # escaping prefix -> clean 400, like PUT
                    self.send_response(400)
                    self.end_headers()
                    return
                body = "\n".join(names).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                src = store._resolve(urllib.parse.unquote(parsed.path))
            except ValueError:
                self.send_response(400)
                self.end_headers()
                return
            if not src.is_file():
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Length", str(src.stat().st_size))
            self.end_headers()
            with open(src, "rb") as f:
                shutil.copyfileobj(f, self.wfile)

    server = ThreadingHTTPServer((host, port), Handler)
    return server, f"http://{host}:{server.server_address[1]}"


class S3Provider(StorageProvider):
    """Gated object-store backend (reference S3Uploader/S3Downloader). This
    image has no egress and no boto3; constructing raises with instructions
    rather than failing at first use."""

    def __init__(self, bucket: str):
        raise RuntimeError(
            "S3/object-store transfer requires network egress and an S3 "
            "client, neither of which is available in this environment. Use "
            "LocalFileSystemProvider against a mounted path, or deploy with "
            f"an object-store client to reach bucket {bucket!r}.")


@dataclasses.dataclass
class TpuProvisioner:
    """Accelerator-pool request builder (reference aws Ec2BoxCreator renders
    EC2 RunInstances; the TPU equivalent renders a queued-resource request).
    ``render()`` produces the request dict a deployment tool would submit."""

    accelerator_type: str = "v5litepod-16"
    runtime_version: str = "tpu-ubuntu2204-base"
    zone: str = "us-central1-a"
    num_slices: int = 1
    preemptible: bool = False

    def render(self, name: str) -> dict:
        return {
            "name": name,
            "accelerator_type": self.accelerator_type,
            "runtime_version": self.runtime_version,
            "zone": self.zone,
            "num_slices": self.num_slices,
            "spot": self.preemptible,
        }
