"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

Reference: eval/Evaluation.java (eval(realOutcomes,guesses):191, stats():352) and
eval/ConfusionMatrix.java. Time-series input ([B,T,C]) is flattened with the label mask
applied, matching BaseEvaluation.evalTimeSeries.

Accumulation happens on host in numpy (it's O(batch) bookkeeping, not TPU work);
the model forward producing the guesses is the jitted TPU path.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class ConfusionMatrix:
    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1) -> None:
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def actual_total(self, cls: int) -> int:
        return int(self.matrix[cls].sum())

    def predicted_total(self, cls: int) -> int:
        return int(self.matrix[:, cls].sum())

    def __str__(self) -> str:
        return str(self.matrix)


class Prediction:
    """Per-example prediction with attached metadata for error attribution
    (reference eval/meta/Prediction.java)."""

    __slots__ = ("actual", "predicted", "record_meta_data")

    def __init__(self, actual: int, predicted: int, record_meta_data=None):
        self.actual = actual
        self.predicted = predicted
        self.record_meta_data = record_meta_data

    def __repr__(self) -> str:
        return (f"Prediction(actual={self.actual}, "
                f"predicted={self.predicted}, meta={self.record_meta_data!r})")


class Evaluation:
    """Classification accumulator.

    ``labels`` attaches class-label names used in ``stats()`` and the rendered
    confusion matrix (reference eval/Evaluation.java labeled constructors);
    ``top_n > 1`` additionally tracks top-N accuracy — a guess counts if the
    true class is among the N highest-probability outputs (reference
    Evaluation(List<String> labels, int topN) and stats() top-N block).
    """

    def __init__(self, n_classes: Optional[int] = None, labels: Optional[list] = None,
                 top_n: int = 1):
        self.labels = list(labels) if labels else None
        self.n_classes = n_classes or (len(labels) if labels else None)
        self.top_n = max(1, int(top_n))
        self.top_n_correct = 0
        self.confusion: Optional[ConfusionMatrix] = None
        self.num_examples = 0
        self._predictions: list = []

    def label_name(self, cls: int) -> str:
        if self.labels and 0 <= cls < len(self.labels):
            return str(self.labels[cls])
        return str(cls)

    def _ensure(self, n: int):
        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None,
             record_meta_data: Optional[list] = None) -> None:
        """labels/predictions: one-hot/probabilities [B,C] or time series [B,T,C]."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:  # [B,T,C] -> flatten with mask
            B, T, C = labels.shape
            labels = labels.reshape(-1, C)
            predictions = predictions.reshape(-1, C)
            if record_meta_data is not None:
                # metadata is per example; replicate across that example's
                # timesteps so flattened rows keep the right attribution
                record_meta_data = [
                    record_meta_data[b] if b < len(record_meta_data) else None
                    for b in range(B) for _ in range(T)]
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
                if record_meta_data is not None:
                    record_meta_data = [m for m, k in
                                        zip(record_meta_data, keep) if k]
        elif mask is not None:  # [B, C] with a per-example mask
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
            if record_meta_data is not None:
                record_meta_data = [m for m, k in
                                    zip(record_meta_data, keep) if k]
        self._ensure(labels.shape[-1])
        actual = labels.argmax(-1)
        guess = predictions.argmax(-1)
        if self.top_n > 1 and len(actual):
            n = min(self.top_n, predictions.shape[-1])
            topk = np.argpartition(predictions, -n, axis=-1)[:, -n:]
            self.top_n_correct += int((topk == actual[:, None]).any(-1).sum())
        else:
            self.top_n_correct += int((actual == guess).sum())
        for i, (a, g) in enumerate(zip(actual, guess)):
            self.confusion.add(int(a), int(g))
            if record_meta_data is not None:
                meta = record_meta_data[i] if i < len(record_meta_data) else None
                self._predictions.append(Prediction(int(a), int(g), meta))
        self.num_examples += len(actual)

    # ---------------------------------------------------- metadata attribution
    def get_prediction_errors(self) -> list:
        """Mispredicted examples with metadata (reference
        Evaluation.getPredictionErrors)."""
        return [p for p in self._predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> list:
        return [p for p in self._predictions if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int) -> list:
        return [p for p in self._predictions if p.predicted == cls]

    def get_predictions(self, actual: int, predicted: int) -> list:
        return [p for p in self._predictions
                if p.actual == actual and p.predicted == predicted]

    # ------------------------------------------------------------------ metrics
    def true_positives(self, cls: int) -> int:
        return self.confusion.get_count(cls, cls)

    def false_positives(self, cls: int) -> int:
        return self.confusion.predicted_total(cls) - self.true_positives(cls)

    def false_negatives(self, cls: int) -> int:
        return self.confusion.actual_total(cls) - self.true_positives(cls)

    def accuracy(self) -> float:
        if self.num_examples == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.num_examples

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            pt = self.confusion.predicted_total(cls)
            return self.true_positives(cls) / pt if pt else 0.0
        vals = [self.precision(c) for c in range(self.n_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            at = self.confusion.actual_total(cls)
            return self.true_positives(cls) / at if at else 0.0
        vals = [self.recall(c) for c in range(self.n_classes)
                if self.confusion.actual_total(c) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def top_n_accuracy(self) -> float:
        """Fraction of examples whose true class was in the top-N guesses
        (reference Evaluation.topNAccuracy())."""
        if self.num_examples == 0:
            return 0.0
        return self.top_n_correct / self.num_examples

    def stats(self) -> str:
        """Human-readable summary with class-label names when provided
        (reference Evaluation.stats():352)."""
        lines = ["==========================Scores========================================",
                 f" Examples:  {self.num_examples}",
                 f" Accuracy:  {self.accuracy():.4f}"]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.top_n_accuracy():.4f}")
        lines += [f" Precision: {self.precision():.4f}",
                  f" Recall:    {self.recall():.4f}",
                  f" F1 Score:  {self.f1():.4f}",
                  "========================================================================"]
        if self.confusion is not None and self.n_classes <= 20:
            names = [self.label_name(c) for c in range(self.n_classes)]
            w = max(len(n) for n in names)
            lines.append("Confusion matrix (rows = actual, cols = predicted):")
            cols = " ".join(f"{n:>{max(w, 5)}}" for n in names)
            lines.append(f"{'':>{w}} {cols}")
            for a in range(self.n_classes):
                row = " ".join(f"{self.confusion.get_count(a, p):>{max(w, 5)}}"
                               for p in range(self.n_classes))
                lines.append(f"{names[a]:>{w}} {row}")
        return "\n".join(lines)

    def merge(self, other: "Evaluation") -> "Evaluation":
        """Combine accumulated stats (used by distributed evaluation, reference
        spark impl/multilayer/evaluation/)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.n_classes = other.n_classes
            self.confusion = ConfusionMatrix(other.n_classes)
        if self.labels is None:
            self.labels = other.labels
        self.confusion.matrix += other.confusion.matrix
        self.num_examples += other.num_examples
        self.top_n_correct += other.top_n_correct
        self._predictions.extend(other._predictions)
        return self
