"""ROC / AUC evaluation with thresholded accumulation.

Reference: eval/ROC.java and ROCMultiClass.java — fixed threshold steps so accumulation
is streaming and O(steps) memory, same design here.
"""
from __future__ import annotations


import numpy as np


class ROC:
    """Binary ROC. Labels: [B,1] {0,1} or [B,2] one-hot; predictions same shape
    (probability of class 1 in column -1)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self.tp = np.zeros(threshold_steps + 1, np.int64)
        self.fp = np.zeros(threshold_steps + 1, np.int64)
        self.tn = np.zeros(threshold_steps + 1, np.int64)
        self.fn = np.zeros(threshold_steps + 1, np.int64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            pos = labels[:, 1] > 0.5
            prob = predictions[:, 1]
        else:
            pos = labels.reshape(-1) > 0.5
            prob = predictions.reshape(-1)
        for i, t in enumerate(self.thresholds):
            pred_pos = prob >= t
            self.tp[i] += int(np.sum(pred_pos & pos))
            self.fp[i] += int(np.sum(pred_pos & ~pos))
            self.fn[i] += int(np.sum(~pred_pos & pos))
            self.tn[i] += int(np.sum(~pred_pos & ~pos))

    def get_roc_curve(self):
        """[(threshold, fpr, tpr)] points."""
        pts = []
        for i, t in enumerate(self.thresholds):
            tpr = self.tp[i] / max(self.tp[i] + self.fn[i], 1)
            fpr = self.fp[i] / max(self.fp[i] + self.tn[i], 1)
            pts.append((float(t), float(fpr), float(tpr)))
        return pts

    def calculate_auc(self) -> float:
        """Trapezoidal AUC over the thresholded curve (reference ROC.calculateAUC)."""
        pts = sorted((fpr, tpr) for _, fpr, tpr in self.get_roc_curve())
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        return float(np.trapezoid(ys, xs))


class ROCMultiClass:
    """One-vs-all ROC per class (reference ROCMultiClass.java)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = threshold_steps
        self.per_class: dict[int, ROC] = {}

    def eval(self, labels: np.ndarray, predictions: np.ndarray) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        n_classes = labels.shape[-1]
        for c in range(n_classes):
            roc = self.per_class.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c:c + 1], predictions[:, c:c + 1])

    def calculate_auc(self, cls: int) -> float:
        return self.per_class[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.per_class.values()]))
