from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass
