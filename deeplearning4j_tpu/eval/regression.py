"""Regression evaluation: per-column MSE/MAE/RMSE/RSE/correlation.

Reference: eval/RegressionEvaluation.java.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, column_names: Optional[list] = None):
        self.column_names = column_names
        self._sum_sq_err = None
        self._sum_abs_err = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None
        self.n = 0

    def _ensure(self, c):
        if self._sum_sq_err is None:
            z = lambda: np.zeros(c, np.float64)
            self._sum_sq_err, self._sum_abs_err = z(), z()
            self._sum_label, self._sum_label_sq = z(), z()
            self._sum_pred, self._sum_pred_sq, self._sum_label_pred = z(), z(), z()

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None) -> None:
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            C = labels.shape[-1]
            labels = labels.reshape(-1, C)
            predictions = predictions.reshape(-1, C)
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        self._ensure(labels.shape[-1])
        err = labels - predictions
        self._sum_sq_err += (err ** 2).sum(0)
        self._sum_abs_err += np.abs(err).sum(0)
        self._sum_label += labels.sum(0)
        self._sum_label_sq += (labels ** 2).sum(0)
        self._sum_pred += predictions.sum(0)
        self._sum_pred_sq += (predictions ** 2).sum(0)
        self._sum_label_pred += (labels * predictions).sum(0)
        self.n += labels.shape[0]

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_sq_err[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs_err[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self._sum_sq_err[col] / self.n))

    def relative_squared_error(self, col: int = 0) -> float:
        mean_label = self._sum_label[col] / self.n
        denom = self._sum_label_sq[col] - self.n * mean_label ** 2
        return float(self._sum_sq_err[col] / denom) if denom else 0.0

    def correlation_r2(self, col: int = 0) -> float:
        n = self.n
        num = n * self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col]
        den = (np.sqrt(n * self._sum_label_sq[col] - self._sum_label[col] ** 2)
               * np.sqrt(n * self._sum_pred_sq[col] - self._sum_pred[col] ** 2))
        return float(num / den) if den else 0.0

    def num_columns(self) -> int:
        return len(self._sum_sq_err) if self._sum_sq_err is not None else 0

    def stats(self) -> str:
        cols = self.num_columns()
        lines = ["Column    MSE            MAE            RMSE           RSE            R"]
        for c in range(cols):
            name = (self.column_names[c] if self.column_names and c < len(self.column_names)
                    else f"col_{c}")
            lines.append(f"{name:<9} {self.mean_squared_error(c):<14.6g} "
                         f"{self.mean_absolute_error(c):<14.6g} "
                         f"{self.root_mean_squared_error(c):<14.6g} "
                         f"{self.relative_squared_error(c):<14.6g} "
                         f"{self.correlation_r2(c):<.6g}")
        return "\n".join(lines)
