"""Japanese/Korean tokenizer factories + stopwords + moving window.

Reference: deeplearning4j-nlp-japanese (a bundled kuromoji fork, 6.9k LoC) and
deeplearning4j-nlp-korean (SURVEY.md §2.5), plus StopWords and the
moving-window iterator in deeplearning4j-nlp text/.

The reference ships dictionary-based morphological analyzers; this image has
no such dictionaries, so these tokenizers are script-aware segmenters: they
split on Unicode-script boundaries (kanji/hiragana/katakana/latin runs for
Japanese; hangul syllable runs + common particle stripping for Korean). The
TokenizerFactory seam is identical, so a dictionary-backed implementation can
replace them without touching callers.
"""
from __future__ import annotations

import re
from typing import Iterator, List, Sequence

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory

# Common English stopwords (reference stopwords resource file)
STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it no
not of on or such that the their then there these they this to was will with
he she his her him i me my we our you your had has have were been being do
does did so than too very can could should would may might must shall
""".split())


class StopWords:
    """Reference org.deeplearning4j.text.stopwords.StopWords."""

    @staticmethod
    def get_stop_words() -> List[str]:
        return sorted(STOP_WORDS)

    @staticmethod
    def is_stop_word(w: str) -> bool:
        return w.lower() in STOP_WORDS


_JA_RUNS = re.compile(
    "([一-鿿]+"      # kanji
    "|[぀-ゟ]+"      # hiragana
    "|[゠-ヿー]+"  # katakana
    "|[A-Za-z0-9]+"
    "|[^一-鿿぀-ゟ゠-ヿーA-Za-z0-9\\s]+)")


class JapaneseTokenizerFactory(TokenizerFactory):
    """Script-run segmentation for Japanese text (kuromoji-seam equivalent).

    Adjacent runs of the same script class become one token; trailing
    hiragana after a kanji run (okurigana/particles) stays separate, which
    approximates bunsetsu boundaries well enough for embedding pipelines."""

    def create(self, text: str) -> Tokenizer:
        tokens = [m.group(0) for m in _JA_RUNS.finditer(text)]
        return Tokenizer(self._apply_pre(tokens))


_KO_PARTICLES = ("은", "는", "이", "가", "을", "를", "에", "의", "로", "과",
                 "와", "도", "만", "에서", "까지", "부터", "하고")
_KO_RUNS = re.compile("([가-힯]+|[A-Za-z0-9]+|[^가-힯"
                      "A-Za-z0-9\\s]+)")


class KoreanTokenizerFactory(TokenizerFactory):
    """Hangul-run segmentation with common particle stripping (open-korean-
    text-seam equivalent)."""

    def __init__(self, strip_particles: bool = True):
        super().__init__()
        self.strip_particles = strip_particles

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for m in _KO_RUNS.finditer(text):
            tok = m.group(0)
            if self.strip_particles and len(tok) > 1:
                for p in sorted(_KO_PARTICLES, key=len, reverse=True):
                    if tok.endswith(p) and len(tok) > len(p):
                        tok = tok[: -len(p)]
                        break
            tokens.append(tok)
        return Tokenizer(self._apply_pre(tokens))


class Windows:
    """Moving context windows over a token sequence (reference
    text/movingwindow/Windows.java): fixed-size windows centered on each
    token, padded with <s>/</s> edge markers."""

    @staticmethod
    def windows(tokens: Sequence[str], window_size: int = 5) -> Iterator[List[str]]:
        half = window_size // 2
        padded = ["<s>"] * half + list(tokens) + ["</s>"] * half
        for i in range(len(tokens)):
            yield padded[i:i + 2 * half + 1]
