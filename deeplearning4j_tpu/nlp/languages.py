"""Japanese/Korean tokenizer factories + stopwords + moving window.

Reference: deeplearning4j-nlp-japanese (a bundled kuromoji fork, 6.9k LoC) and
deeplearning4j-nlp-korean (SURVEY.md §2.5), plus StopWords and the
moving-window iterator in deeplearning4j-nlp text/.

The reference ships dictionary-based morphological analyzers. Japanese here
uses the same lattice-Viterbi architecture as kuromoji (lexicon edges +
character-class unknown-word edges, minimum-cost path) with an embedded
closed-class mini-lexicon instead of the 6.9k-LoC IPADIC fork this image
can't carry; Korean is hangul-run segmentation with josa stripping. The
TokenizerFactory seam is identical, so a full-dictionary implementation can
replace them without touching callers.
"""
from __future__ import annotations

import re
from typing import Iterator, List, Sequence

from deeplearning4j_tpu.nlp.tokenization import Tokenizer, TokenizerFactory

# Common English stopwords (reference stopwords resource file)
STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it no
not of on or such that the their then there these they this to was will with
he she his her him i me my we our you your had has have were been being do
does did so than too very can could should would may might must shall
""".split())


class StopWords:
    """Reference org.deeplearning4j.text.stopwords.StopWords."""

    @staticmethod
    def get_stop_words() -> List[str]:
        return sorted(STOP_WORDS)

    @staticmethod
    def is_stop_word(w: str) -> bool:
        return w.lower() in STOP_WORDS


# --------------------------------------------------------------------- Japanese
# Kuromoji-architecture lattice segmenter: Viterbi over (embedded-lexicon
# edges + character-class unknown-word edges), per-edge word costs plus a
# connection penalty. The reference vendors a 6.9k-LoC kuromoji fork whose
# quality comes from the full IPADIC dictionary; this image ships no such
# dictionary, so the embedded lexicon covers (a) closed-class morphemes —
# particles, copulas, auxiliaries, demonstratives, frequent adverbs — and
# (b) generated conjugation paradigms (~1000 surface forms from ~100
# high-frequency verb/adjective stems via the standard godan/ichidan/
# i-adjective rules below). Coverage gap vs IPADIC, stated precisely:
# IPADIC carries ~300k open-class entries (nouns, names, rare verbs) with
# per-pair connection costs and POS tags; here open-class words fall to
# script-run unknown edges (whole kanji/katakana runs kept intact), no POS
# is emitted, and compound kanji runs without a lexicon boundary are not
# split (e.g. 毎日日本語 stays one run). Same algorithm, miniature
# dictionary; the TokenizerFactory seam is unchanged, so a full-dictionary
# build can drop in without touching callers.

_JA_LEXICON = {
    # case/topic particles (lowest cost: always split off)
    "は": 100, "が": 100, "を": 100, "に": 100, "で": 100, "と": 100,
    "の": 100, "へ": 110, "も": 110, "や": 120, "か": 130, "ね": 140,
    "よ": 140, "な": 150, "から": 115, "まで": 115, "より": 125,
    "ので": 125, "のに": 130, "には": 120, "では": 120, "とは": 125,
    "でも": 125, "だけ": 125, "など": 125, "について": 130,
    # copulas / auxiliaries / light verbs
    "です": 140, "だ": 160, "である": 150, "でした": 150, "ます": 140,
    "ました": 145, "ません": 145, "する": 170, "した": 170, "して": 170,
    "します": 160, "いる": 175, "いた": 180, "いて": 180, "ある": 175,
    "あった": 180, "ない": 170, "なかった": 180, "なる": 180, "なった": 185,
    "れる": 185, "られる": 185, "せる": 190, "たい": 185, "という": 150,
    # frequent function nouns / demonstratives
    "こと": 180, "もの": 190, "ため": 185, "とき": 190, "ところ": 195,
    "これ": 180, "それ": 180, "あれ": 190, "どれ": 195, "この": 175,
    "その": 175, "あの": 185, "ここ": 190, "そこ": 190, "わたし": 190,
    "私": 200, "人": 260, "日": 270, "年": 270, "月": 270, "時": 270,
    # frequent adverbs / temporal nouns / question words
    "とても": 220, "少し": 220, "すこし": 230, "もう": 220, "まだ": 220,
    "また": 225, "すぐ": 225, "よく": 230, "たくさん": 225, "ちょっと": 225,
    "いつも": 225, "時々": 235, "今日": 230, "明日": 230, "昨日": 230,
    "今": 250, "毎日": 235, "今朝": 240, "今年": 240, "何": 240,
    "いつ": 240, "どこ": 235, "だれ": 240, "誰": 245, "なぜ": 240,
    "どう": 235, "こう": 250, "そう": 240,
}

# ---- conjugation paradigms -------------------------------------------------
# IPADIC's verb/adjective coverage is mostly paradigm expansion; the same
# expansion is generated here programmatically for a list of high-frequency
# stems. Each surface form enters the lexicon at a flat cost so the lattice
# prefers one conjugated-verb edge over unknown-run + auxiliary splits.
# (Original stem lists + standard textbook conjugation rules — no dictionary
# data is copied.)

#: godan row -> (nai-stem a, masu-stem i, e-stem, o-stem, te-form suffix)
_GODAN_ROWS = {
    "う": ("わ", "い", "え", "お", "って"),
    "く": ("か", "き", "け", "こ", "いて"),
    "ぐ": ("が", "ぎ", "げ", "ご", "いで"),
    "す": ("さ", "し", "せ", "そ", "して"),
    "つ": ("た", "ち", "て", "と", "って"),
    "ぬ": ("な", "に", "ね", "の", "んで"),
    "ぶ": ("ば", "び", "べ", "ぼ", "んで"),
    "む": ("ま", "み", "め", "も", "んで"),
    "る": ("ら", "り", "れ", "ろ", "って"),
}

_GODAN_VERBS = """行く 書く 聞く 歩く 働く 着く 泳ぐ 急ぐ 話す 出す 貸す 返す
待つ 持つ 立つ 死ぬ 遊ぶ 呼ぶ 飛ぶ 読む 飲む 住む 休む 頼む 買う 使う 会う
言う 思う 歌う 習う 作る 乗る 帰る 入る 走る 知る 売る 送る 取る 終わる
始まる 分かる かかる もらう""".split()

_ICHIDAN_VERBS = """見る 食べる 寝る 起きる 出る 着る 開ける 閉める 教える
覚える 忘れる 借りる 降りる できる 考える 伝える 見せる 入れる 続ける
あげる くれる 調べる 始める 決める 感じる 信じる 受ける 与える 比べる
別れる 生まれる 変える 迎える 助ける 育てる 捨てる 並べる 逃げる
投げる 上げる 下げる 集める 認める 求める 進める 止める 辞める
答える 数える 加える 抱える 超える 越える""".split()

_I_ADJECTIVES = """高い 安い 新しい 古い 大きい 小さい 良い 悪い 早い 遅い
長い 短い 暑い 寒い 楽しい 難しい 面白い 美しい 強い 弱い 近い 遠い 多い
少ない 白い 黒い 赤い 青い 忙しい 嬉しい""".split()

_CONJ_COST = 240  # between closed-class morphemes and bare-noun kanji runs


#: surface -> POS for generated paradigm forms (merged into _JA_POS below)
_PARADIGM_POS: dict = {}


def _expand_verb_paradigms(lexicon: dict) -> None:
    def add(form: str, pos: str = "動詞") -> None:
        lexicon.setdefault(form, _CONJ_COST)
        _PARADIGM_POS.setdefault(form, pos)

    for verb in _GODAN_VERBS:
        stem, ending = verb[:-1], verb[-1]
        a, i, e, o, te_suf = _GODAN_ROWS[ending]
        te = stem + ("って" if verb == "行く" else te_suf)  # 行く is irregular
        past = te[:-1] + ("だ" if te.endswith("で") else "た")
        for f in (verb, te, past, stem + i, stem + i + "ます",
                  stem + i + "ました", stem + i + "ません", stem + a + "ない",
                  stem + a + "なかった", stem + e + "る", stem + e + "ば",
                  stem + o + "う", stem + i + "たい"):
            add(f)
    for verb in _ICHIDAN_VERBS:
        stem = verb[:-1]
        for f in (verb, stem + "て", stem + "た", stem + "ない",
                  stem + "なかった", stem + "ます", stem + "ました",
                  stem + "ません", stem + "られる", stem + "よう",
                  stem + "れば", stem + "たい"):
            add(f)
    for adj in _I_ADJECTIVES:
        stem = adj[:-1]
        for f in (adj, stem + "く", stem + "くて", stem + "かった",
                  stem + "くない", stem + "くなかった", stem + "ければ"):
            add(f, pos="形容詞")


_expand_verb_paradigms(_JA_LEXICON)

# ---- POS table (kuromoji emits POS per token; coarse tag set here) --------
_JA_POS = {}
for _w in ("は が を に で と の へ も や か ね よ な から まで より ので "
           "のに には では とは でも だけ など について").split():
    _JA_POS[_w] = "助詞"
for _w in ("です だ である でした ます ました ません れる られる せる "
           "たい ない なかった").split():
    _JA_POS[_w] = "助動詞"
for _v in (_GODAN_VERBS + _ICHIDAN_VERBS
           + ("する した して します いる いた いて ある あった なる "
              "なった という").split()):
    _JA_POS.setdefault(_v, "動詞")
for _a in _I_ADJECTIVES:
    _JA_POS.setdefault(_a, "形容詞")
for _w, _p in _PARADIGM_POS.items():
    _JA_POS.setdefault(_w, _p)

# ---- open-class dictionary (nlp/ja_lexicon.py): the hand-built stand-in
# for IPADIC's open-class coverage. Merged AFTER the closed-class tables so
# function-word costs keep priority; adds ~1.1k nouns/verbal-nouns/
# na-adjectives/proper nouns with POS tags, which is what lets compound
# kanji runs split at real word boundaries (日本語勉強中 -> 日本語/勉強/中).
from deeplearning4j_tpu.nlp.ja_lexicon import OPEN_CLASS as _JA_OPEN_CLASS

for _w, (_cost, _pos) in _JA_OPEN_CLASS.items():
    _JA_LEXICON.setdefault(_w, _cost)
    _JA_POS.setdefault(_w, _pos)

_JA_MAX_WORD = max(len(w) for w in _JA_LEXICON)
_JA_EDGE_COST = 50          # connection penalty per lattice edge
_JA_UNK_BASE = 700          # unknown-word base cost
_JA_UNK_PER_CHAR = {"kanji": 120, "hiragana": 400, "katakana": 60,
                    "latin": 40, "other": 80}


def _ja_char_class(ch: str) -> str:
    o = ord(ch)
    if 0x4E00 <= o <= 0x9FFF:
        return "kanji"
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or ch == "ー":
        return "katakana"
    if ch.isascii() and (ch.isalnum()):
        return "latin"
    return "other"


def _ja_viterbi(chunk: str) -> List[str]:
    """Minimum-cost segmentation of one whitespace-free chunk."""
    n = len(chunk)
    INF = float("inf")
    best = [INF] * (n + 1)
    back = [0] * (n + 1)
    best[0] = 0.0
    for i in range(n):
        if best[i] == INF:
            continue
        # lexicon edges
        for L in range(1, min(_JA_MAX_WORD, n - i) + 1):
            cost = _JA_LEXICON.get(chunk[i:i + L])
            if cost is not None:
                c = best[i] + cost + _JA_EDGE_COST
                if c < best[i + L]:
                    best[i + L] = c
                    back[i + L] = i
        # unknown edges: every prefix of the maximal same-class run
        # (kuromoji's unknown-word processing groups by character class);
        # the per-edge base cost keeps whole runs preferred unless a lexicon
        # split (e.g. a particle boundary inside a hiragana run) pays for it
        cls = _ja_char_class(chunk[i])
        j = i + 1
        while j < n and _ja_char_class(chunk[j]) == cls:
            j += 1
        per = _JA_UNK_PER_CHAR[cls]
        for end in range(i + 1, j + 1):
            c = best[i] + _JA_UNK_BASE + per * (end - i) + _JA_EDGE_COST
            if c < best[end]:
                best[end] = c
                back[end] = i
    out = []
    pos = n
    while pos > 0:
        out.append(chunk[back[pos]:pos])
        pos = back[pos]
    return out[::-1]


def ja_pos(token: str) -> str:
    """Coarse POS for a segmented token (kuromoji's per-token POS seam):
    lexicon tag if known, else a char-class-derived unknown tag."""
    pos = _JA_POS.get(token)
    if pos is not None:
        return pos
    if not token:
        return "記号"
    cls = _ja_char_class(token[0])
    return {"kanji": "名詞", "katakana": "名詞", "latin": "名詞",
            "hiragana": "未知語", "other": "記号"}[cls]


def ja_tokenize_with_pos(text: str) -> List[tuple]:
    """(surface, pos) pairs — the kuromoji Token.getPartOfSpeech analog."""
    out = []
    for chunk in text.split():
        out.extend((t, ja_pos(t)) for t in _ja_viterbi(chunk))
    return out


class JapaneseTokenizerFactory(TokenizerFactory):
    """Lattice-Viterbi segmentation for Japanese (kuromoji-seam equivalent;
    reference deeplearning4j-nlp-japanese). Closed-class morphemes and the
    hand-built open-class dictionary (nlp/ja_lexicon.py, ~1.1k entries with
    POS) come from the merged lexicon; unknown words are maximal script
    runs with per-class costs — e.g. 私は東京へ行きます ->
    [私, は, 東京, へ, 行きます] with particles split correctly. POS per
    token via ``ja_tokenize_with_pos``/``ja_pos``."""

    def create(self, text: str) -> Tokenizer:
        tokens: List[str] = []
        for chunk in text.split():
            tokens.extend(_ja_viterbi(chunk))
        return Tokenizer(self._apply_pre(tokens))


_KO_PARTICLES = ("은", "는", "이", "가", "을", "를", "에", "의", "로", "과",
                 "와", "도", "만", "에서", "까지", "부터", "하고")
_KO_RUNS = re.compile("([가-힯]+|[A-Za-z0-9]+|[^가-힯"
                      "A-Za-z0-9\\s]+)")


class KoreanTokenizerFactory(TokenizerFactory):
    """Hangul-run segmentation with common particle stripping (open-korean-
    text-seam equivalent)."""

    def __init__(self, strip_particles: bool = True):
        super().__init__()
        self.strip_particles = strip_particles

    def create(self, text: str) -> Tokenizer:
        tokens = []
        for m in _KO_RUNS.finditer(text):
            tok = m.group(0)
            if self.strip_particles and len(tok) > 1:
                for p in sorted(_KO_PARTICLES, key=len, reverse=True):
                    if tok.endswith(p) and len(tok) > len(p):
                        tok = tok[: -len(p)]
                        break
            tokens.append(tok)
        return Tokenizer(self._apply_pre(tokens))


class Windows:
    """Moving context windows over a token sequence (reference
    text/movingwindow/Windows.java): fixed-size windows centered on each
    token, padded with <s>/</s> edge markers."""

    @staticmethod
    def windows(tokens: Sequence[str], window_size: int = 5) -> Iterator[List[str]]:
        half = window_size // 2
        padded = ["<s>"] * half + list(tokens) + ["</s>"] * half
        for i in range(len(tokens)):
            yield padded[i:i + 2 * half + 1]
