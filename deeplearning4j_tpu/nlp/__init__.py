from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

__all__ = ["Word2Vec", "ParagraphVectors", "Glove", "SequenceVectors"]
