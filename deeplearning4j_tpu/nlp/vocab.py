"""Vocabulary construction + Huffman coding.

Reference: models/word2vec/wordstore/VocabConstructor.java:33 (parallel count +
min-frequency filter + Huffman tree), models/word2vec/Huffman.java,
wordstore/inmemory/AbstractCache.java (word<->index maps, counts).
"""
from __future__ import annotations

import heapq
import itertools
from typing import Dict, Iterable, List, Optional, Sequence

MAX_CODE_LENGTH = 40  # classic word2vec bound (reference Huffman.java MAX_CODE_LENGTH)


class VocabWord:
    """reference models/word2vec/VocabWord.java — element with frequency,
    Huffman code/points, and index."""

    __slots__ = ("word", "count", "index", "code", "points", "labels")

    def __init__(self, word: str, count: float = 1.0):
        self.word = word
        self.count = count
        self.index = -1
        self.code: List[int] = []
        self.points: List[int] = []
        self.labels: List[str] = []

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count}, index={self.index})"


class VocabCache:
    """In-memory vocab store (reference AbstractCache/InMemoryLookupCache)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._index: List[VocabWord] = []
        self.total_word_count = 0.0

    # ------------------------------------------------------------------ build
    def add_token(self, word: str, count: float = 1.0) -> VocabWord:
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word, 0.0)
            self._words[word] = vw
        vw.count += count
        self.total_word_count += count
        return vw

    def finish(self, min_word_frequency: int = 1,
               special: Sequence[str] = ()) -> None:
        """Drop rare words and assign indices by descending frequency
        (reference VocabConstructor.buildJointVocabulary)."""
        kept = [vw for vw in self._words.values()
                if vw.count >= min_word_frequency or vw.word in special]
        kept.sort(key=lambda vw: (-vw.count, vw.word))
        self._words = {vw.word: vw for vw in kept}
        self._index = kept
        for i, vw in enumerate(kept):
            vw.index = i
        self.total_word_count = sum(vw.count for vw in kept)

    # ------------------------------------------------------------------ access
    def __contains__(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_at(self, index: int) -> VocabWord:
        return self._index[index]

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw is not None else -1

    def num_words(self) -> int:
        return len(self._index)

    def words(self) -> List[str]:
        return [vw.word for vw in self._index]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._index)


def build_huffman(cache: VocabCache) -> None:
    """Assign Huffman codes+points to every vocab word (reference Huffman.java).

    points[d] is the index of the d-th inner node on the root→word path (inner
    nodes indexed into syn1); code[d] is the branch taken (0/1). Ordering matches
    the classic word2vec convention: points from root down, including the root,
    excluding the leaf.
    """
    n = cache.num_words()
    if n == 0:
        return
    # heap of (count, tiebreak, node_id); leaves are 0..n-1, inner n..2n-2
    heap: list = [(vw.count, i, i) for i, vw in enumerate(cache.vocab_words())]
    heapq.heapify(heap)
    parent: Dict[int, int] = {}
    branch: Dict[int, int] = {}
    next_id = n
    while len(heap) > 1:
        c1, _, a = heapq.heappop(heap)
        c2, _, b = heapq.heappop(heap)
        parent[a] = next_id
        parent[b] = next_id
        branch[a] = 0
        branch[b] = 1
        heapq.heappush(heap, (c1 + c2, next_id, next_id))
        next_id += 1
    root = heap[0][2] if heap else None
    for i, vw in enumerate(cache.vocab_words()):
        code: List[int] = []
        points: List[int] = []
        node = i
        while node != root and node in parent:
            code.append(branch[node])
            node = parent[node]
            points.append(node - n)  # inner-node index into syn1
        code.reverse()
        points.reverse()
        vw.code = code[:MAX_CODE_LENGTH]
        vw.points = points[:MAX_CODE_LENGTH]


class VocabConstructor:
    """Builds a VocabCache from token-sequence sources
    (reference VocabConstructor.java:33)."""

    def __init__(self, min_word_frequency: int = 1, build_huffman_tree: bool = True,
                 special: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.build_huffman_tree = build_huffman_tree
        self.special = tuple(special)

    def build_joint_vocabulary(self, sequences: Iterable[Sequence[str]]) -> VocabCache:
        cache = VocabCache()
        for seq in sequences:
            for token in seq:
                if token:
                    cache.add_token(token)
        cache.finish(self.min_word_frequency, self.special)
        if self.build_huffman_tree:
            build_huffman(cache)
        return cache

    def build_from_file(self, path: str, tokenizer_factory=None) -> VocabCache:
        """Build the vocabulary straight from a text file.

        For the default whitespace tokenizer (optionally with
        CommonPreprocessor) over ASCII corpora, counting runs in the native
        C++ runtime with worker threads — the analog of the reference's
        parallel VocabConstructor count phase (VocabConstructor.java:33).
        Any other tokenizer, a missing native runtime, or non-ASCII content
        falls back to the Python pipeline with identical results.
        """
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor, DefaultTokenizerFactory)

        pre = getattr(tokenizer_factory, "_pre", None)
        native_ok = tokenizer_factory is None or (
            type(tokenizer_factory) is DefaultTokenizerFactory
            and (pre is None or type(pre) is CommonPreprocessor))
        if native_ok:
            from deeplearning4j_tpu import nativert
            counts = nativert.count_tokens_file(
                str(path), common_preprocess=pre is not None)
            if counts is not None:
                cache = VocabCache()
                for word, count in counts:
                    if word:
                        cache.add_token(word, float(count))
                # specials are guaranteed present (same as the callers of
                # build_joint_vocabulary, which append one occurrence each)
                for sp in self.special:
                    cache.add_token(sp)
                cache.finish(self.min_word_frequency, self.special)
                if self.build_huffman_tree:
                    build_huffman(cache)
                return cache

        if tokenizer_factory is None:
            tokenizer_factory = DefaultTokenizerFactory()
        with open(path, "r", encoding="utf-8") as f:
            seqs = (tokenizer_factory.create(line).get_tokens()
                    for line in f if line.strip())
            return self.build_joint_vocabulary(
                itertools.chain(seqs, ([sp] for sp in self.special)))
