"""Embedding lookup table.

Reference: models/embeddings/inmemory/InMemoryLookupTable.java:55 — syn0 (input
vectors), syn1 (hierarchical-softmax inner nodes), syn1neg (negative-sampling
output vectors), plus the unigram^0.75 sampling table. The reference's expTable
(precomputed sigmoid) is unnecessary here — sigmoid runs exact on the VPU.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache


class InMemoryLookupTable:
    def __init__(self, cache: VocabCache, vector_length: int, seed: int = 42,
                 use_hs: bool = True, negative: int = 0):
        self.cache = cache
        self.vector_length = vector_length
        self.seed = seed
        self.use_hs = use_hs
        self.negative = negative
        self.syn0: Optional[jax.Array] = None
        self.syn1: Optional[jax.Array] = None
        self.syn1neg: Optional[jax.Array] = None
        self.cum_table: Optional[jax.Array] = None

    def reset_weights(self) -> None:
        """Uniform(-0.5,0.5)/dim init, zero outputs (reference resetWeights)."""
        n = self.cache.num_words()
        d = self.vector_length
        rng = np.random.default_rng(self.seed)
        self.syn0 = jnp.asarray(
            (rng.random((n, d), np.float32) - 0.5) / d)
        if self.use_hs:
            self.syn1 = jnp.zeros((max(n - 1, 1), d), jnp.float32)
        if self.negative > 0:
            self.syn1neg = jnp.zeros((n, d), jnp.float32)
            counts = np.array([vw.count for vw in self.cache.vocab_words()],
                              np.float64)
            probs = counts ** 0.75
            probs /= probs.sum()
            self.cum_table = jnp.asarray(np.cumsum(probs).astype(np.float32))

    # ------------------------------------------------------------------ vectors API
    def vector(self, word: str) -> Optional[np.ndarray]:
        idx = self.cache.index_of(word)
        if idx < 0 or self.syn0 is None:
            return None
        return np.asarray(self.syn0[idx])

    def set_vector(self, word: str, vec) -> None:
        idx = self.cache.index_of(word)
        if idx < 0:
            raise KeyError(word)
        self.syn0 = self.syn0.at[idx].set(jnp.asarray(vec))
