"""Word2Vec facade over SequenceVectors (reference models/word2vec/Word2Vec.java:32).

Builder-style configuration mirroring the reference's Word2Vec.Builder.
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from deeplearning4j_tpu.nlp.iterators import SentenceIterator
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory


class Word2Vec(SequenceVectors):
    def __init__(self, **kwargs):
        kwargs.setdefault("vector_length", 100)
        super().__init__(**kwargs)
        self.tokenizer_factory: TokenizerFactory = DefaultTokenizerFactory()
        self.sentence_iterator: Optional[SentenceIterator] = None

    # ------------------------------------------------------------------ builder
    class Builder:
        def __init__(self):
            self._kw = {}
            self._tokenizer = None
            self._iterator = None

        def layer_size(self, n: int):
            self._kw["vector_length"] = n
            return self

        def window_size(self, n: int):
            self._kw["window"] = n
            return self

        def min_word_frequency(self, n: int):
            self._kw["min_word_frequency"] = n
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def min_learning_rate(self, lr: float):
            self._kw["min_learning_rate"] = lr
            return self

        def negative_sample(self, k: int):
            self._kw["negative"] = k
            if k > 0:
                self._kw.setdefault("use_hierarchic_softmax", False)
            return self

        def use_hierarchic_softmax(self, flag: bool):
            self._kw["use_hierarchic_softmax"] = flag
            return self

        def sampling(self, t: float):
            self._kw["sampling"] = t
            return self

        def epochs(self, n: int):
            self._kw["epochs"] = n
            return self

        def iterations(self, n: int):
            self._kw["iterations"] = n
            return self

        def batch_size(self, n: int):
            self._kw["batch_size"] = n
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def elements_learning_algorithm(self, name: str):
            self._kw["elements_learning_algorithm"] = (
                "cbow" if "cbow" in name.lower() else "skipgram")
            return self

        def window(self, n: int):
            return self.window_size(n)

        def tokenizer_factory(self, tf: TokenizerFactory):
            self._tokenizer = tf
            return self

        def iterate(self, it):
            self._iterator = it
            return self

        def build(self) -> "Word2Vec":
            w2v = Word2Vec(**self._kw)
            if self._tokenizer is not None:
                w2v.tokenizer_factory = self._tokenizer
            if self._iterator is not None:
                w2v.sentence_iterator = self._iterator
            return w2v

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    # ------------------------------------------------------------------ fit
    def _tokenized(self) -> List[List[str]]:
        if self.sentence_iterator is None:
            raise ValueError("No sentence iterator set — use builder().iterate(...)")
        if hasattr(self.sentence_iterator, "reset"):
            self.sentence_iterator.reset()
        return [self.tokenizer_factory.create(s).get_tokens()
                for s in self.sentence_iterator]

    def fit(self, sequences: Optional[Iterable] = None, labels=None) -> None:
        if sequences is None:
            sequences = self._tokenized()
        super().fit(sequences, labels)
