"""GloVe embeddings.

Reference: models/glove/Glove.java (438 LoC) + glove/count/ (cooccurrence
counting). Host-side symmetric-window cooccurrence counting with 1/distance
weighting, then jit-compiled AdaGrad updates on shuffled (i, j, Xij) batches —
the reference's per-pair AdaGrad loop becomes one batched device step.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors


class Glove(SequenceVectors):
    def __init__(self, *, x_max: float = 100.0, alpha: float = 0.75,
                 learning_rate: float = 0.05, symmetric: bool = True, **kwargs):
        kwargs.setdefault("learning_rate", learning_rate)
        kwargs.setdefault("use_hierarchic_softmax", False)
        super().__init__(**kwargs)
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.bias: Optional[jax.Array] = None
        self.bias_ctx: Optional[jax.Array] = None
        self.ctx_vectors: Optional[jax.Array] = None

    # ------------------------------------------------------------------ builder
    class Builder:
        def __init__(self):
            self._kw = {}

        def layer_size(self, n: int):
            self._kw["vector_length"] = n
            return self

        def window_size(self, n: int):
            self._kw["window"] = n
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def epochs(self, n: int):
            self._kw["epochs"] = n
            return self

        def min_word_frequency(self, n: int):
            self._kw["min_word_frequency"] = n
            return self

        def x_max(self, v: float):
            self._kw["x_max"] = v
            return self

        def alpha(self, v: float):
            self._kw["alpha"] = v
            return self

        def symmetric(self, flag: bool):
            self._kw["symmetric"] = flag
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def batch_size(self, n: int):
            self._kw["batch_size"] = n
            return self

        def build(self) -> "Glove":
            return Glove(**self._kw)

    @staticmethod
    def builder() -> "Glove.Builder":
        return Glove.Builder()

    # ------------------------------------------------------------------ training
    def _count_cooccurrences(self, seqs: List[List[int]]):
        counts: dict = defaultdict(float)
        for seq in seqs:
            for pos, w in enumerate(seq):
                lo = max(0, pos - self.window)
                for j in range(lo, pos):
                    c = seq[j]
                    weight = 1.0 / (pos - j)
                    counts[(w, c)] += weight
                    if self.symmetric:
                        counts[(c, w)] += weight
        return counts

    def fit(self, sequences: Iterable[Sequence[str]], labels=None) -> None:
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list)
        cache = self.vocab
        n, d = cache.num_words(), self.vector_length
        idx_seqs = [[cache.index_of(t) for t in s] for s in seq_list]
        idx_seqs = [[i for i in s if i >= 0] for s in idx_seqs]
        counts = self._count_cooccurrences(idx_seqs)
        if not counts:
            return
        pairs = np.array(list(counts.keys()), np.int32)
        xij = np.array(list(counts.values()), np.float32)

        rng = np.random.default_rng(self.seed)
        w = jnp.asarray((rng.random((n, d), np.float32) - 0.5) / d)
        wc = jnp.asarray((rng.random((n, d), np.float32) - 0.5) / d)
        b = jnp.zeros((n,), jnp.float32)
        bc = jnp.zeros((n,), jnp.float32)
        hist = (jnp.ones((n, d), jnp.float32), jnp.ones((n, d), jnp.float32),
                jnp.ones((n,), jnp.float32), jnp.ones((n,), jnp.float32))

        x_max, alpha, lr = self.x_max, self.alpha, self.learning_rate

        @jax.jit
        def glove_step(w, wc, b, bc, hist, wi, ci, x):
            hw, hwc, hb, hbc = hist
            vi, vj = w[wi], wc[ci]                  # (B, D)
            diff = (jnp.sum(vi * vj, -1) + b[wi] + bc[ci] - jnp.log(x))
            fx = jnp.minimum((x / x_max) ** alpha, 1.0)
            g = fx * diff                            # (B,)
            loss = 0.5 * jnp.mean(fx * diff * diff)
            gw = g[:, None] * vj
            gwc = g[:, None] * vi
            # AdaGrad: accumulate squared grads then scale
            hw = hw.at[wi].add(gw * gw)
            hwc = hwc.at[ci].add(gwc * gwc)
            hb = hb.at[wi].add(g * g)
            hbc = hbc.at[ci].add(g * g)
            w = w.at[wi].add(-lr * gw / jnp.sqrt(hw[wi]))
            wc = wc.at[ci].add(-lr * gwc / jnp.sqrt(hwc[ci]))
            b = b.at[wi].add(-lr * g / jnp.sqrt(hb[wi]))
            bc = bc.at[ci].add(-lr * g / jnp.sqrt(hbc[ci]))
            return w, wc, b, bc, (hw, hwc, hb, hbc), loss

        B = self.batch_size
        n_pairs = pairs.shape[0]
        for _ in range(self.epochs):
            order = rng.permutation(n_pairs)
            for s in range(0, n_pairs, B):
                sel = order[s:s + B]
                if len(sel) < B:  # pad to fixed shape, weight 0 ⇒ no-op via x=1,f=0
                    pad = rng.integers(0, n_pairs, B - len(sel))
                    sel = np.concatenate([sel, pad])
                wi = jnp.asarray(pairs[sel, 0])
                ci = jnp.asarray(pairs[sel, 1])
                x = jnp.asarray(xij[sel])
                w, wc, b, bc, hist, loss = glove_step(w, wc, b, bc, hist, wi, ci, x)

        self.lookup.syn0 = w + wc  # GloVe convention: sum of word+context vectors
        self.ctx_vectors = wc
        self.bias, self.bias_ctx = b, bc
