"""Tokenizer SPIs (reference deeplearning4j-nlp text/tokenization/**:
TokenizerFactory, Tokenizer, TokenPreProcess impls, DefaultTokenizer,
NGramTokenizerFactory, stopwords).
"""
from __future__ import annotations

import re
from typing import List, Optional

# reference resource stopwords (text/stopwords) — the standard English list
DEFAULT_STOP_WORDS = frozenset("""a an and are as at be but by for if in into is it
no not of on or such that the their then there these they this to was will with
""".split())


class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer for common English endings (reference EndingPreProcessor.java)."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("."):
            token = token[:-1]
        if token.endswith("ly"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        return token


class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens
        self._pos = 0

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> List[str]:
        return list(self._tokens)


class TokenizerFactory:
    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def _apply_pre(self, tokens: List[str]) -> List[str]:
        if self._pre is None:
            return tokens
        out = [self._pre.pre_process(t) for t in tokens]
        return [t for t in out if t]

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace/word-char tokenization (reference DefaultTokenizerFactory —
    java.util.StringTokenizer semantics)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._apply_pre(text.split()))


class NGramTokenizerFactory(TokenizerFactory):
    """Emits n-grams joined by spaces (reference NGramTokenizerFactory.java)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        super().__init__()
        self.base = base
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        words = self.base.create(text).get_tokens()
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(0, len(words) - n + 1):
                out.append(" ".join(words[i:i + n]))
        return Tokenizer(self._apply_pre(out))
