"""Distributed Word2Vec/GloVe-style training: TextPipeline + param averaging.

Reference: deeplearning4j-scaleout dl4j-spark-nlp (SURVEY.md §2.4) —
`TextPipeline` (tokenize + vocab build via Spark accumulators, broadcast
vocab) and spark/models/embeddings/word2vec/Word2Vec.java:61 (per-partition
First/SecondIterationFunction skip-gram training, driver-side averaging).

TPU-native redesign: the corpus is sharded across ``num_workers`` logical
workers; each worker trains the jitted skip-gram/CBOW step (nlp/learning.py)
over its shard starting from the broadcast parameters, and after every
averaging round the workers' {syn0, syn1, syn1neg} are averaged — exactly the
BSP parameter-averaging semantics of the Spark master. Workers here execute
in-process (one TPU chip): the worker loop is the unit a multi-host deployment
maps onto jax.distributed processes, with the average becoming one psum over
DCN.
"""
from __future__ import annotations

import copy
from concurrent.futures import ThreadPoolExecutor
from collections import Counter
from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor, DefaultTokenizerFactory, TokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor, build_huffman
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class TextPipeline:
    """Corpus -> token sequences + vocabulary (reference spark TextPipeline:
    tokenization and word counts accumulate in parallel, then the vocab is
    'broadcast' — here: shared by reference)."""

    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1, num_workers: int = 4):
        if tokenizer_factory is None:
            tokenizer_factory = DefaultTokenizerFactory()
            tokenizer_factory.set_token_pre_processor(CommonPreprocessor())
        self.tokenizer_factory = tokenizer_factory
        self.min_word_frequency = min_word_frequency
        self.num_workers = max(1, num_workers)

    def tokenize(self, sentences: Iterable[str]) -> List[List[str]]:
        sents = list(sentences)
        chunk = max(1, len(sents) // self.num_workers)
        chunks = [sents[i:i + chunk] for i in range(0, len(sents), chunk)]

        def work(part: List[str]) -> List[List[str]]:
            return [self.tokenizer_factory.create(s).get_tokens()
                    for s in part]

        with ThreadPoolExecutor(max_workers=self.num_workers) as ex:
            parts = list(ex.map(work, chunks))
        return [t for part in parts for t in part]

    def word_counts(self, token_seqs: List[List[str]]) -> Counter:
        chunk = max(1, len(token_seqs) // self.num_workers)
        chunks = [token_seqs[i:i + chunk]
                  for i in range(0, len(token_seqs), chunk)]

        def count(part) -> Counter:
            c: Counter = Counter()
            for seq in part:
                c.update(seq)
            return c

        with ThreadPoolExecutor(max_workers=self.num_workers) as ex:
            counters = list(ex.map(count, chunks))
        total: Counter = Counter()
        for c in counters:
            total.update(c)
        return total

    def build_vocab(self, token_seqs: List[List[str]]) -> VocabCache:
        constructor = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False)
        cache = constructor.build_joint_vocabulary(token_seqs)
        build_huffman(cache)
        return cache


class SparkWord2Vec:
    """Parameter-averaging distributed Word2Vec (reference dl4j-spark-nlp
    Word2Vec). Named for parity; the execution substrate is the TPU runtime,
    not Spark."""

    def __init__(self, num_workers: int = 4, averaging_rounds: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 **word2vec_kwargs):
        self.num_workers = max(1, num_workers)
        self.averaging_rounds = max(1, averaging_rounds)
        self.pipeline = TextPipeline(tokenizer_factory,
                                     word2vec_kwargs.get("min_word_frequency", 1),
                                     self.num_workers)
        self._kw = dict(word2vec_kwargs)
        self._kw.setdefault("epochs", 1)
        self.master: Optional[Word2Vec] = None

    # ------------------------------------------------------------------ training
    def fit(self, sentences: Iterable[str]) -> "SparkWord2Vec":
        token_seqs = self.pipeline.tokenize(sentences)
        cache = self.pipeline.build_vocab(token_seqs)

        self.master = Word2Vec(**self._kw)
        self.master.vocab = cache
        from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
        self.master.lookup = InMemoryLookupTable(
            cache, self.master.vector_length, seed=self.master.seed,
            use_hs=self.master.use_hs, negative=self.master.negative)
        self.master.lookup.reset_weights()

        shards = [token_seqs[i::self.num_workers]
                  for i in range(self.num_workers)]
        shards = [s for s in shards if s]
        for _ in range(self.averaging_rounds):
            results = []
            for widx, shard in enumerate(shards):
                worker = Word2Vec(**{**self._kw, "seed":
                                     self.master.seed + widx})
                worker.vocab = cache                      # broadcast vocab
                worker.lookup = _clone_lookup(self.master.lookup)  # broadcast
                worker.fit(shard)
                results.append(worker.lookup)
            # BSP average (reference processResults: params / count)
            lt = self.master.lookup
            lt.syn0 = _mean([r.syn0 for r in results])
            if lt.syn1 is not None:
                lt.syn1 = _mean([r.syn1 for r in results])
            if lt.syn1neg is not None:
                lt.syn1neg = _mean([r.syn1neg for r in results])
        return self

    # ------------------------------------------------------------------ queries
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.master.lookup.vector(word)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        na = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(np.dot(va, vb) / na) if na else 0.0

    def words_nearest(self, word: str, n: int = 5) -> List[str]:
        v = self.get_word_vector(word)
        if v is None:
            return []
        lt = self.master.lookup
        syn0 = np.asarray(lt.syn0)
        norms = np.linalg.norm(syn0, axis=1) * (np.linalg.norm(v) or 1.0)
        sims = syn0 @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        words = [self.master.vocab.word_at(int(i)).word for i in order]
        return [w for w in words if w != word][:n]


def _clone_lookup(lt):
    """Deep-copy the device arrays: the jitted train step donates its
    param buffers, so each worker must own distinct copies of the broadcast."""
    import jax.numpy as jnp
    new = copy.copy(lt)
    new.syn0 = jnp.array(lt.syn0)
    if lt.syn1 is not None:
        new.syn1 = jnp.array(lt.syn1)
    if lt.syn1neg is not None:
        new.syn1neg = jnp.array(lt.syn1neg)
    return new


def _mean(arrays: Sequence) -> np.ndarray:
    import jax.numpy as jnp
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out / len(arrays)
