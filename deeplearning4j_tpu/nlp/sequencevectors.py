"""SequenceVectors: the generic embedding-training engine.

Reference: models/sequencevectors/SequenceVectors.java:50 — fit():164-293 builds
vocab, resets weights, then streams sequences through trainSequence:295 with a
pluggable learning algorithm (SkipGram/CBOW for elements, DBOW/DM for
sequences). The reference parallelizes with VectorCalculationsThreads feeding
batched native ops; here pair generation stays on host and training is one
jit-compiled device step per fixed-size pair batch (learning.py).

Supports element learning (skip-gram / CBOW) and sequence learning (PV-DBOW /
PV-DM) over arbitrary token sequences — Word2Vec, ParagraphVectors and DeepWalk
are facades over this engine, as in the reference.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import BatchAccumulator, make_train_step
from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor

Array = jax.Array


class SequenceVectors:
    def __init__(self, *, vector_length: int = 100, window: int = 5,
                 use_hierarchic_softmax: bool = True, negative: int = 0,
                 learning_rate: float = 0.025, min_learning_rate: float = 1e-4,
                 epochs: int = 1, iterations: int = 1,
                 min_word_frequency: int = 1, batch_size: int = 512,
                 sampling: float = 0.0, seed: int = 42,
                 elements_learning_algorithm: str = "skipgram",
                 sequence_learning_algorithm: Optional[str] = None,
                 train_elements: bool = True, train_sequences: bool = False,
                 special_tokens: Sequence[str] = ()):
        if elements_learning_algorithm not in ("skipgram", "cbow"):
            raise ValueError(f"Unknown elements algorithm: {elements_learning_algorithm}")
        if sequence_learning_algorithm not in (None, "dbow", "dm"):
            raise ValueError(f"Unknown sequence algorithm: {sequence_learning_algorithm}")
        self.vector_length = vector_length
        self.window = window
        self.use_hs = use_hierarchic_softmax
        self.negative = negative
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.iterations = iterations
        self.min_word_frequency = min_word_frequency
        self.batch_size = batch_size
        self.sampling = sampling
        self.seed = seed
        self.elements_algo = elements_learning_algorithm
        self.sequence_algo = sequence_learning_algorithm
        self.train_elements = train_elements
        self.train_sequences = train_sequences
        self.special_tokens = tuple(special_tokens)

        self.vocab: Optional[VocabCache] = None
        self.lookup: Optional[InMemoryLookupTable] = None
        self._np_rng = np.random.default_rng(seed)
        self._key = jax.random.PRNGKey(seed)

    # ------------------------------------------------------------------ vocab
    def build_vocab(self, sequences: Iterable[Sequence[str]],
                    labels: Optional[Iterable[Sequence[str]]] = None) -> None:
        """Build joint vocabulary; sequence labels (for DBOW/DM) become vocab
        entries too, as in the reference (labels live in the same lookup table)."""
        all_seqs: List[Sequence[str]] = [list(s) for s in sequences]
        specials = list(self.special_tokens)
        if labels is not None:
            label_lists = [list(ls) for ls in labels]
            for ls in label_lists:
                specials.extend(ls)
        constructor = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False, special=specials)
        cache = constructor.build_joint_vocabulary(
            all_seqs + ([[lab] for lab in specials] if specials else []))
        from deeplearning4j_tpu.nlp.vocab import build_huffman

        build_huffman(cache)
        self.vocab = cache
        self.lookup = InMemoryLookupTable(
            cache, self.vector_length, seed=self.seed, use_hs=self.use_hs,
            negative=self.negative)
        self.lookup.reset_weights()

    def build_vocab_from_file(self, path: str, tokenizer_factory=None) -> None:
        """Vocabulary straight from a corpus file: the count phase runs in the
        native C++ runtime with worker threads when the tokenizer allows it
        (VocabConstructor.build_from_file), mirroring the reference's parallel
        vocab construction (VocabConstructor.java:33). Defaults to this
        vectorizer's configured tokenizer (Word2Vec.tokenizer_factory) so the
        vocab is built with the same tokenization training will use."""
        if tokenizer_factory is None:
            tokenizer_factory = getattr(self, "tokenizer_factory", None)
        constructor = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=True, special=list(self.special_tokens))
        cache = constructor.build_from_file(path, tokenizer_factory)
        self.vocab = cache
        self.lookup = InMemoryLookupTable(
            cache, self.vector_length, seed=self.seed, use_hs=self.use_hs,
            negative=self.negative)
        self.lookup.reset_weights()

    # ------------------------------------------------------------------ training
    def fit(self, sequences: Iterable[Sequence[str]],
            labels: Optional[List[Sequence[str]]] = None) -> None:
        seq_list = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seq_list, labels)
        cache = self.vocab
        lt = self.lookup
        max_code = max((len(vw.code) for vw in cache.vocab_words()), default=1) or 1
        # CBOW/DM consume up to 2*window context tokens (+1 label for DM)
        W = 1 if (self.elements_algo == "skipgram" and self.sequence_algo != "dm") \
            else 2 * self.window + 1
        step = make_train_step(self.use_hs, self.negative)
        acc = BatchAccumulator(self.batch_size, W, max_code, cache.num_words())

        total_words = sum(len(s) for s in seq_list) * self.epochs * self.iterations
        processed = 0
        alpha = self.learning_rate
        cum = lt.cum_table if lt.cum_table is not None else jnp.zeros((1,), jnp.float32)

        def run(batch):
            nonlocal lt
            self._key, sub = jax.random.split(self._key)
            syn0, syn1, syn1neg = step(
                lt.syn0,
                lt.syn1 if lt.syn1 is not None else jnp.zeros((1, self.vector_length)),
                lt.syn1neg if lt.syn1neg is not None else jnp.zeros((1, self.vector_length)),
                cum, batch, jnp.float32(alpha), sub)
            lt.syn0 = syn0
            if lt.syn1 is not None:
                lt.syn1 = syn1
            if lt.syn1neg is not None:
                lt.syn1neg = syn1neg

        for _ in range(self.epochs):
            for si, seq in enumerate(seq_list):
                for _ in range(self.iterations):
                    seq_labels = (labels[si] if labels and si < len(labels) else [])
                    processed += len(seq)
                    alpha = max(
                        self.min_learning_rate,
                        self.learning_rate * (1 - processed / max(1, total_words)))
                    for batch in self._train_sequence(seq, seq_labels, acc):
                        run(batch)
        final = acc.flush()
        if final is not None:
            run(final)

    def _train_sequence(self, seq: Sequence[str], seq_labels: Sequence[str], acc):
        """Generate training pairs for one sequence (reference trainSequence:295 →
        SkipGram/CBOW.learnSequence). Dynamic window shrink + subsampling as in
        word2vec."""
        cache = self.vocab
        idxs = [cache.index_of(t) for t in seq]
        idxs = [i for i in idxs if i >= 0]
        if self.sampling > 0:
            total = cache.total_word_count
            kept = []
            for i in idxs:
                f = cache.word_at(i).count / total
                keep_p = (np.sqrt(f / self.sampling) + 1) * (self.sampling / f)
                if keep_p >= 1.0 or self._np_rng.random() < keep_p:
                    kept.append(i)
            idxs = kept
        label_idxs = [cache.index_of(l) for l in seq_labels]
        label_idxs = [i for i in label_idxs if i >= 0]

        for pos, center in enumerate(idxs):
            b = int(self._np_rng.integers(0, self.window))  # dynamic window
            lo = max(0, pos - (self.window - b))
            hi = min(len(idxs), pos + (self.window - b) + 1)
            context = [idxs[j] for j in range(lo, hi) if j != pos]
            vw = cache.word_at(center)
            if self.train_elements:
                if self.elements_algo == "skipgram":
                    # each context token predicts the center word
                    for c in context:
                        batch = acc.add([c], center, vw.points, vw.code)
                        if batch is not None:
                            yield batch
                else:  # cbow: masked mean of context predicts center
                    if context:
                        batch = acc.add(context, center, vw.points, vw.code)
                        if batch is not None:
                            yield batch
            if self.train_sequences and label_idxs:
                for lab in label_idxs:
                    if self.sequence_algo == "dbow":
                        # doc vector predicts each word (PV-DBOW)
                        batch = acc.add([lab], center, vw.points, vw.code)
                    else:
                        # PV-DM: doc vector + context mean predicts center
                        batch = acc.add(context + [lab], center, vw.points, vw.code)
                    if batch is not None:
                        yield batch

    # ------------------------------------------------------------------ vectors API
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self.lookup.vector(word) if self.lookup else None

    def _normed_syn0(self) -> np.ndarray:
        syn0 = np.asarray(self.lookup.syn0)
        norms = np.linalg.norm(syn0, axis=1, keepdims=True)
        return syn0 / np.maximum(norms, 1e-12)

    def similarity(self, w1: str, w2: str) -> float:
        v1, v2 = self.get_word_vector(w1), self.get_word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        denom = np.linalg.norm(v1) * np.linalg.norm(v2)
        return float(np.dot(v1, v2) / max(denom, 1e-12))

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            vec = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        if vec is None:
            return []
        normed = self._normed_syn0()
        sims = normed @ (vec / max(np.linalg.norm(vec), 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at(int(i)).word
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out
