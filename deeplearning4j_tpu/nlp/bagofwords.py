"""Bag-of-words / TF-IDF vectorizers.

Reference: bagofwords/vectorizer/{BagOfWordsVectorizer,TfidfVectorizer}.java —
fit a vocabulary over documents, then transform text to sparse count /
tf-idf row vectors (dense numpy here; rows feed DataSet pipelines).
"""
from __future__ import annotations

from typing import Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory, TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor


class BaseTextVectorizer:
    def __init__(self, tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1, stop_words: Iterable[str] = ()):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = frozenset(stop_words)
        self.vocab: Optional[VocabCache] = None
        self.doc_freq: Optional[np.ndarray] = None
        self.n_docs = 0

    def _tokens(self, text: str) -> List[str]:
        toks = self.tokenizer_factory.create(text).get_tokens()
        return [t for t in toks if t and t not in self.stop_words]

    def fit(self, documents: Iterable[str]) -> "BaseTextVectorizer":
        docs = [self._tokens(d) for d in documents]
        self.n_docs = len(docs)
        self.vocab = VocabConstructor(
            min_word_frequency=self.min_word_frequency,
            build_huffman_tree=False).build_joint_vocabulary(docs)
        df = np.zeros(self.vocab.num_words(), np.float64)
        for toks in docs:
            for i in {self.vocab.index_of(t) for t in toks}:
                if i >= 0:
                    df[i] += 1
        self.doc_freq = df
        return self

    def transform(self, document: str) -> np.ndarray:
        raise NotImplementedError

    def fit_transform(self, documents: Iterable[str]) -> np.ndarray:
        docs = list(documents)
        self.fit(docs)
        return np.stack([self.transform(d) for d in docs])


class BagOfWordsVectorizer(BaseTextVectorizer):
    def transform(self, document: str) -> np.ndarray:
        row = np.zeros(self.vocab.num_words(), np.float32)
        for t in self._tokens(document):
            i = self.vocab.index_of(t)
            if i >= 0:
                row[i] += 1.0
        return row


class TfidfVectorizer(BaseTextVectorizer):
    """tf * log(N / df) weighting (reference TfidfVectorizer.java)."""

    def transform(self, document: str) -> np.ndarray:
        counts = np.zeros(self.vocab.num_words(), np.float32)
        toks = self._tokens(document)
        for t in toks:
            i = self.vocab.index_of(t)
            if i >= 0:
                counts[i] += 1.0
        tf = counts / max(len(toks), 1)
        idf = np.log(np.maximum(self.n_docs, 1)
                     / np.maximum(self.doc_freq, 1.0)).astype(np.float32)
        return tf * idf
