"""Batched embedding-training kernels.

Reference: models/embeddings/learning/impl/elements/{SkipGram,CBOW}.java — the
reference queues AggregateSkipGram ops and executes the batch natively
(SkipGram.java:168-178). TPU-native equivalent: ONE jit-compiled step per batch
of training pairs, with gathers + scatter-adds over the embedding matrices.
Hierarchical softmax (:225) and negative sampling (:258) both supported; CBOW
and PV-DM reuse the same kernel with multi-token inputs (masked mean).

Update convention matches classic word2vec (and the reference): for a pair the
input vector is h = mean(syn0[ctx]) (single token for skip-gram), outputs are
the target word's Huffman path (syn1) and/or sampled negatives (syn1neg);
g = (label - sigmoid(h·v)) * lr; each input token receives the full
accumulated gradient (no 1/n scaling on the backward, as in word2vec C).
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class PairBatch(NamedTuple):
    """One padded batch of training pairs (host-assembled, device-consumed)."""

    ctx: Array        # (B, W) int32 input-token indices
    ctx_mask: Array   # (B, W) float32 — 1 for real input tokens
    target: Array     # (B,) int32 target-word indices
    points: Array     # (B, L) int32 Huffman inner-node indices (HS)
    codes: Array      # (B, L) float32 Huffman branch codes (HS)
    code_mask: Array  # (B, L) float32 — 1 for real code positions
    pair_mask: Array  # (B,) float32 — 1 for real (non-padding) pairs
    update_dest: Array  # (B, W) int32 where input-gradients are scattered


#: vocab-size ceiling for the dense one-hot-matmul update path (auto mode).
#: A (rows, V) one-hot times (rows, D) update is exact scatter-add math on
#: the MXU — but it rewrites the WHOLE V x D table per chunk, so its HBM
#: traffic is O(V*D) regardless of how few rows changed. Round-5 on-chip
#: A/B (v5e, scripts/bench_log.jsonl): scatter wins at every measured vocab
#: — 946k vs 645k pairs/s at V=10k, 1.09M vs 968k at V=2048 — so the dense
#: path is OFF by default (ceiling 0) and remains an explicit opt-in via
#: DL4J_W2V_DENSE=1 for dtypes/shapes where a future chip's scatter unit is
#: the bottleneck.
DENSE_UPDATE_MAX_VOCAB = int(os.environ.get("DL4J_W2V_DENSE_MAX_VOCAB", "0"))


def resolve_dense_update(n_words: int) -> bool:
    """THE auto heuristic for the dense one-hot-matmul update path, shared
    with bench.py's A/B labeling: DL4J_W2V_DENSE=0/1 forces it; otherwise
    dense iff the vocab fits the ceiling AND there is an MXU (on CPU a
    one-hot matmul is orders of magnitude slower than scatter)."""
    env = os.environ.get("DL4J_W2V_DENSE")
    if env is not None:
        return env == "1"
    return (n_words <= DENSE_UPDATE_MAX_VOCAB
            and jax.default_backend() not in ("cpu",))


def _scatter_add(table, idx_flat, upd_flat, dense: bool):
    """table[idx] += upd with identical semantics on both paths: duplicate
    indices accumulate, out-of-range indices are dropped (one_hot yields a
    zero row exactly where scatter mode="drop" skips). precision=HIGHEST
    keeps the MXU pass float32-exact — without it TPU einsum rounds the
    updates to bfloat16 and the two paths diverge numerically."""
    if dense:
        oh = jax.nn.one_hot(idx_flat, table.shape[0], dtype=upd_flat.dtype)
        return table + jnp.einsum("nv,nd->vd", oh, upd_flat,
                                  precision=jax.lax.Precision.HIGHEST)
    return table.at[idx_flat].add(upd_flat, mode="drop")


def make_train_step(use_hs: bool, negative: int, chunk: int = 64,
                    dense_update: Optional[bool] = None):
    """Returns jitted step(syn0, syn1, syn1neg, cum_table, batch, lr, key).

    The batch is applied in sequential sub-chunks of ``chunk`` pairs via
    ``lax.scan`` inside the one compiled step: frequent rows (e.g. the Huffman
    root, in nearly every pair) would otherwise receive hundreds of colliding
    scatter-adds computed from one stale snapshot and diverge; chunking bounds
    the staleness to ``chunk`` pairs while keeping a single device dispatch
    (word2vec's update semantics are fully online, one pair at a time).

    ``dense_update`` routes the embedding-table updates through one-hot
    matmuls (MXU) instead of XLA scatter; None = auto via
    resolve_dense_update (an explicit argument always wins over the
    DL4J_W2V_DENSE env override so A/B twins stay distinct).
    DL4J_W2V_CHUNK=N overrides the chunk size at build time."""
    chunk = int(os.environ.get("DL4J_W2V_CHUNK", chunk))

    def apply_chunk(syn0, syn1, syn1neg, cum_table, batch: PairBatch, lr, key):
        B, W = batch.ctx.shape
        d = syn0.shape[1]
        dense = (dense_update if dense_update is not None
                 else resolve_dense_update(syn0.shape[0]))
        ctx_vecs = syn0[batch.ctx]                        # (B, W, D)
        cmask = batch.ctx_mask[..., None]                 # (B, W, 1)
        counts = jnp.maximum(jnp.sum(batch.ctx_mask, 1, keepdims=True), 1.0)
        h = jnp.sum(ctx_vecs * cmask, axis=1) / counts    # (B, D) masked mean
        neu1e = jnp.zeros((B, d), syn0.dtype)             # input-gradient accum

        if use_hs:
            p_vecs = syn1[batch.points]                   # (B, L, D)
            f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, p_vecs))
            # word2vec label = 1 - code
            g = ((1.0 - batch.codes - f) * lr
                 * batch.code_mask * batch.pair_mask[:, None])  # (B, L)
            neu1e = neu1e + jnp.einsum("bl,bld->bd", g, p_vecs)
            dsyn1 = jnp.einsum("bl,bd->bld", g, h)
            syn1 = _scatter_add(syn1, batch.points.reshape(-1),
                                dsyn1.reshape(-1, d), dense)

        if negative > 0:
            k = negative
            u = jax.random.uniform(key, (B, k))
            negs = jnp.searchsorted(cum_table, u).astype(jnp.int32)  # (B, k)
            tgts = jnp.concatenate([batch.target[:, None], negs], axis=1)  # (B,1+k)
            labels = jnp.concatenate(
                [jnp.ones((B, 1)), jnp.zeros((B, k))], axis=1)
            # sampled negative == true target ⇒ skip (word2vec: continue)
            valid = jnp.concatenate(
                [jnp.ones((B, 1), bool), negs != batch.target[:, None]], axis=1)
            n_vecs = syn1neg[tgts]                        # (B, 1+k, D)
            f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, n_vecs))
            g = ((labels - f) * lr * valid
                 * batch.pair_mask[:, None])              # (B, 1+k)
            neu1e = neu1e + jnp.einsum("bk,bkd->bd", g, n_vecs)
            dneg = jnp.einsum("bk,bd->bkd", g, h)
            syn1neg = _scatter_add(syn1neg, tgts.reshape(-1),
                                   dneg.reshape(-1, d), dense)

        # scatter the accumulated input gradient to every real input token
        upd = (neu1e[:, None, :] * cmask
               * batch.pair_mask[:, None, None])          # (B, W, D)
        syn0 = _scatter_add(syn0, batch.update_dest.reshape(-1),
                            upd.reshape(-1, d), dense)
        return syn0, syn1, syn1neg

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(syn0, syn1, syn1neg, cum_table, batch: PairBatch, lr, key):
        B = batch.ctx.shape[0]
        S = min(chunk, B)
        if B % S != 0:  # lint: recompile-hazard-ok (trace-time chunk sizing; B is the fixed accumulator size, static under jit)
            S = B
        C = B // S
        chunked = jax.tree_util.tree_map(
            lambda a: a.reshape((C, S) + a.shape[1:]), batch)
        keys = jax.random.split(key, C)

        def body(carry, xs):
            s0, s1, sn = carry
            b, k = xs
            s0, s1, sn = apply_chunk(s0, s1, sn, cum_table, b, lr, k)
            return (s0, s1, sn), None

        (syn0, syn1, syn1neg), _ = jax.lax.scan(
            body, (syn0, syn1, syn1neg), (chunked, keys))
        return syn0, syn1, syn1neg

    return step


class BatchAccumulator:
    """Host-side pair accumulator producing fixed-shape PairBatches (replaces the
    reference's Aggregate op queue; fixed shapes keep one compiled step)."""

    def __init__(self, batch_size: int, window_width: int, code_length: int,
                 n_words: int):
        self.B = batch_size
        self.W = window_width
        self.L = code_length
        self.n_words = n_words
        self._rows: list = []

    def add(self, ctx_indices, target_idx: int, points, codes,
            update_dest=None) -> Optional[PairBatch]:
        self._rows.append((ctx_indices, target_idx, points, codes,
                           update_dest if update_dest is not None else ctx_indices))
        if len(self._rows) >= self.B:
            return self.flush()
        return None

    def flush(self) -> Optional[PairBatch]:
        if not self._rows:
            return None
        B, W, L = self.B, self.W, self.L
        ctx = np.zeros((B, W), np.int32)
        cmask = np.zeros((B, W), np.float32)
        tgt = np.zeros((B,), np.int32)
        pts = np.zeros((B, L), np.int32)
        codes = np.zeros((B, L), np.float32)
        pmask = np.zeros((B, L), np.float32)
        pair_mask = np.zeros((B,), np.float32)
        dest = np.full((B, W), self.n_words, np.int32)  # OOB ⇒ dropped by scatter
        for i, (c, t, p, cd, ud) in enumerate(self._rows):
            nc = min(len(c), W)
            ctx[i, :nc] = c[:nc]
            cmask[i, :nc] = 1.0
            dest[i, :nc] = ud[:nc]
            tgt[i] = t
            npts = min(len(p), L)
            pts[i, :npts] = p[:npts]
            codes[i, :npts] = cd[:npts]
            pmask[i, :npts] = 1.0
            pair_mask[i] = 1.0
        self._rows = []
        return PairBatch(jnp.asarray(ctx), jnp.asarray(cmask), jnp.asarray(tgt),
                         jnp.asarray(pts), jnp.asarray(codes), jnp.asarray(pmask),
                         jnp.asarray(pair_mask), jnp.asarray(dest))
