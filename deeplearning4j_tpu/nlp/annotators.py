"""Text annotation pipeline: sentence/token/stem/PoS annotators.

Reference: deeplearning4j-nlp-uima (SURVEY.md §2.5) — UIMA analysis engines
(SentenceAnnotator, TokenizerAnnotator, StemmerAnnotator, PoStagger) composed
into a pipeline over a CAS. Here the CAS is a plain ``Annotation`` document
object and annotators are composable callables — same pipeline shape without
the UIMA framework. The stemmer is a Porter-lite suffix stripper and the PoS
tagger a compact rule/lexicon tagger (the reference reaches comparable
components through bundled UIMA models).
"""
from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence


@dataclasses.dataclass
class Token:
    text: str
    begin: int
    end: int
    stem: Optional[str] = None
    pos: Optional[str] = None


@dataclasses.dataclass
class Sentence:
    text: str
    begin: int
    end: int
    tokens: List[Token] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Annotation:
    """The document being annotated (UIMA CAS equivalent)."""

    text: str
    sentences: List[Sentence] = dataclasses.field(default_factory=list)


class Annotator:
    def process(self, cas: Annotation) -> Annotation:
        raise NotImplementedError


class SentenceAnnotator(Annotator):
    """Sentence segmentation on terminal punctuation (reference
    SentenceAnnotator wrapping the UIMA sentence detector)."""

    _BOUNDARY = re.compile(r"(?<=[.!?])\s+")

    def process(self, cas: Annotation) -> Annotation:
        pos = 0
        for part in self._BOUNDARY.split(cas.text):
            if part.strip():
                begin = cas.text.index(part, pos)
                cas.sentences.append(
                    Sentence(part, begin, begin + len(part)))
                pos = begin + len(part)
        return cas


class TokenizerAnnotator(Annotator):
    """Word tokenization inside each sentence (reference TokenizerAnnotator)."""

    _TOKEN = re.compile(r"\w+(?:'\w+)?|[^\w\s]")

    def process(self, cas: Annotation) -> Annotation:
        for s in cas.sentences:
            for m in self._TOKEN.finditer(s.text):
                s.tokens.append(Token(m.group(), s.begin + m.start(),
                                      s.begin + m.end()))
        return cas


class StemmerAnnotator(Annotator):
    """Porter-lite suffix stripping (reference StemmerAnnotator / snowball)."""

    _RULES = [("sses", "ss"), ("ies", "i"), ("ation", "ate"), ("tional", "tion"),
              ("ness", ""), ("ment", ""), ("ing", ""), ("edly", ""),
              ("ed", ""), ("ly", ""), ("s", "")]

    @classmethod
    def stem(cls, w: str) -> str:
        lw = w.lower()
        for suf, rep in cls._RULES:
            if lw.endswith(suf) and len(lw) - len(suf) >= 2:
                return lw[: len(lw) - len(suf)] + rep
        return lw

    def process(self, cas: Annotation) -> Annotation:
        for s in cas.sentences:
            for t in s.tokens:
                t.stem = self.stem(t.text)
        return cas


class PoSTaggerAnnotator(Annotator):
    """Compact rule/lexicon part-of-speech tagger (reference PoStagger)."""

    _DET = {"the", "a", "an", "this", "that", "these", "those"}
    _PRON = {"i", "you", "he", "she", "it", "we", "they", "me", "him", "her",
             "us", "them"}
    _PREP = {"in", "on", "at", "by", "for", "with", "from", "to", "of",
             "over", "under"}
    _CONJ = {"and", "or", "but", "nor", "so", "yet"}
    _AUX = {"is", "are", "was", "were", "be", "been", "am", "has", "have",
            "had", "do", "does", "did", "will", "would", "can", "could"}

    def _tag(self, w: str, prev_tag: Optional[str]) -> str:
        lw = w.lower()
        if not re.match(r"\w", w):
            return "PUNCT"
        if re.fullmatch(r"[\d.,]+", w):
            return "NUM"
        if lw in self._DET:
            return "DET"
        if lw in self._PRON:
            return "PRON"
        if lw in self._PREP:
            return "ADP"
        if lw in self._CONJ:
            return "CCONJ"
        if lw in self._AUX:
            return "AUX"
        if lw.endswith("ly"):
            return "ADV"
        if lw.endswith(("ing", "ed")) and prev_tag in ("AUX", "PRON"):
            return "VERB"
        if lw.endswith(("ous", "ful", "ive", "able", "al", "ic")):
            return "ADJ"
        if prev_tag in ("DET", "ADJ"):
            return "NOUN"
        if prev_tag in ("PRON",):
            return "VERB"
        if w[0].isupper():
            return "PROPN"
        return "NOUN"

    def process(self, cas: Annotation) -> Annotation:
        for s in cas.sentences:
            prev = None
            for t in s.tokens:
                t.pos = self._tag(t.text, prev)
                prev = t.pos
        return cas


class AnnotatorPipeline:
    """Composed analysis engine (reference UIMA AnalysisEngine aggregation)."""

    def __init__(self, annotators: Optional[Sequence[Annotator]] = None):
        self.annotators = list(annotators) if annotators else [
            SentenceAnnotator(), TokenizerAnnotator(), StemmerAnnotator(),
            PoSTaggerAnnotator()]

    def annotate(self, text: str) -> Annotation:
        cas = Annotation(text)
        for a in self.annotators:
            cas = a.process(cas)
        return cas
