"""Sentence/document iterator SPIs (reference text/sentenceiterator/**,
text/documentiterator/**: SentenceIterator, LabelAwareIterator,
LabelsSource, LabelledDocument).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Iterable, Iterator, List, Optional


class SentenceIterator:
    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    """Iterates an in-memory collection (reference CollectionSentenceIterator.java)."""

    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)

    def __iter__(self) -> Iterator[str]:
        return iter(self.sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference BasicLineIterator.java)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self) -> Iterator[str]:
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory (reference FileSentenceIterator.java)."""

    def __init__(self, directory: str):
        self.directory = directory

    def __iter__(self) -> Iterator[str]:
        for root, _, files in os.walk(self.directory):
            for fn in sorted(files):
                with open(os.path.join(root, fn), "r", encoding="utf-8",
                          errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


@dataclasses.dataclass
class LabelledDocument:
    """reference documentiterator/LabelledDocument.java"""

    content: str
    labels: List[str]


class LabelsSource:
    """Generated or user-supplied document labels (reference LabelsSource.java)."""

    def __init__(self, template: str = "DOC_", labels: Optional[List[str]] = None):
        self.template = template
        self._labels = list(labels) if labels else []
        self._counter = 0

    def next_label(self) -> str:
        label = f"{self.template}{self._counter}"
        self._counter += 1
        self._labels.append(label)
        return label

    def get_labels(self) -> List[str]:
        return list(self._labels)


class LabelAwareIterator:
    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Wraps (text, labels) pairs (reference SimpleLabelAwareIterator.java)."""

    def __init__(self, documents: Iterable[LabelledDocument]):
        self.documents = list(documents)

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self.documents)


class LabelAwareListSentenceIterator(LabelAwareIterator):
    """Sentences + auto-generated labels (reference sentenceiterator
    labelaware variants)."""

    def __init__(self, sentences: Iterable[str], labels_source: Optional[LabelsSource] = None):
        self.labels_source = labels_source or LabelsSource()
        self.documents = [LabelledDocument(s, [self.labels_source.next_label()])
                          for s in sentences]

    def __iter__(self) -> Iterator[LabelledDocument]:
        return iter(self.documents)
