"""Word-vector serialization.

Reference: models/embeddings/loader/WordVectorSerializer.java — text format
("word v1 v2 ... vD" per line, optional "count dim" header) and the original
word2vec binary format (header "n d\\n", then word + space + d float32 LE).
"""
from __future__ import annotations


import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.lookup import InMemoryLookupTable
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.vocab import VocabCache, build_huffman


def write_word_vectors(model: SequenceVectors, path: str,
                       binary: bool = False) -> None:
    cache, lt = model.vocab, model.lookup
    syn0 = np.asarray(lt.syn0)
    n, d = syn0.shape
    if binary:
        with open(path, "wb") as f:
            f.write(f"{n} {d}\n".encode())
            for i in range(n):
                word = cache.word_at(i).word
                f.write(word.encode("utf-8") + b" ")
                f.write(syn0[i].astype("<f4").tobytes())
                f.write(b"\n")
    else:
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{n} {d}\n")
            for i in range(n):
                vec = " ".join(f"{v:.6f}" for v in syn0[i])
                f.write(f"{cache.word_at(i).word} {vec}\n")


def read_word_vectors(path: str, binary: bool = False) -> SequenceVectors:
    words: list = []
    vecs: list = []
    if binary:
        with open(path, "rb") as f:
            header = f.readline().decode()
            n, d = (int(x) for x in header.split())
            for _ in range(n):
                chars = bytearray()
                while True:
                    ch = f.read(1)
                    if ch in (b" ", b""):
                        break
                    chars.extend(ch)
                word = chars.decode("utf-8")
                vec = np.frombuffer(f.read(4 * d), dtype="<f4")
                f.read(1)  # trailing newline
                words.append(word)
                vecs.append(vec)
    else:
        with open(path, "r", encoding="utf-8") as f:
            first = f.readline().split()
            if len(first) == 2 and all(t.lstrip("-").isdigit() for t in first):
                n, d = int(first[0]), int(first[1])
            else:  # headerless: first line is already a vector row
                words.append(first[0])
                vecs.append(np.array([float(x) for x in first[1:]], np.float32))
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                vecs.append(np.array([float(x) for x in parts[1:]], np.float32))

    d = len(vecs[0]) if vecs else 0
    model = SequenceVectors(vector_length=d)
    cache = VocabCache()
    for i, w in enumerate(words):
        # counts descend with rank so Huffman/neg-sampling stay well-defined
        cache.add_token(w, count=float(len(words) - i))
    cache.finish(min_word_frequency=0)
    build_huffman(cache)
    model.vocab = cache
    model.lookup = InMemoryLookupTable(cache, d)
    # respect the file's word order (finish() sorts by count, which preserves it)
    syn0 = np.zeros((len(words), d), np.float32)
    for w, v in zip(words, vecs):
        syn0[cache.index_of(w)] = v
    model.lookup.syn0 = jnp.asarray(syn0)
    return model
