"""ParagraphVectors (doc2vec) over SequenceVectors.

Reference: models/paragraphvectors/ParagraphVectors.java (1137 LoC) — labels are
vocab entries sharing the lookup table; PV-DBOW/PV-DM training;
inferVector trains a fresh doc vector against frozen syn0/syn1.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.iterators import LabelAwareIterator, LabelsSource
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory


class ParagraphVectors(SequenceVectors):
    def __init__(self, *, dm: bool = False, **kwargs):
        kwargs.setdefault("train_sequences", True)
        kwargs.setdefault("sequence_learning_algorithm", "dm" if dm else "dbow")
        super().__init__(**kwargs)
        self.tokenizer_factory = DefaultTokenizerFactory()
        self.labels_source = LabelsSource()
        self._docs: Optional[List] = None

    # ------------------------------------------------------------------ builder
    class Builder:
        def __init__(self):
            self._kw = {}
            self._tokenizer = None
            self._iterator: Optional[LabelAwareIterator] = None

        def layer_size(self, n: int):
            self._kw["vector_length"] = n
            return self

        def window_size(self, n: int):
            self._kw["window"] = n
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def min_learning_rate(self, lr: float):
            self._kw["min_learning_rate"] = lr
            return self

        def epochs(self, n: int):
            self._kw["epochs"] = n
            return self

        def min_word_frequency(self, n: int):
            self._kw["min_word_frequency"] = n
            return self

        def negative_sample(self, k: int):
            self._kw["negative"] = k
            if k > 0:
                self._kw.setdefault("use_hierarchic_softmax", False)
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def train_words_vectors(self, flag: bool):
            self._kw["train_elements"] = flag
            return self

        def sequence_learning_algorithm(self, name: str):
            self._kw["sequence_learning_algorithm"] = (
                "dm" if "dm" in name.lower() else "dbow")
            return self

        def tokenizer_factory(self, tf):
            self._tokenizer = tf
            return self

        def iterate(self, it: LabelAwareIterator):
            self._iterator = it
            return self

        def build(self) -> "ParagraphVectors":
            pv = ParagraphVectors(**self._kw)
            if self._tokenizer is not None:
                pv.tokenizer_factory = self._tokenizer
            if self._iterator is not None:
                pv.set_iterator(self._iterator)
            return pv

    @staticmethod
    def builder() -> "ParagraphVectors.Builder":
        return ParagraphVectors.Builder()

    # ------------------------------------------------------------------ data
    def set_iterator(self, iterator: LabelAwareIterator) -> None:
        self._docs = list(iterator)

    def fit(self, sequences: Optional[Iterable] = None, labels=None) -> None:
        if sequences is None:
            if self._docs is None:
                raise ValueError("No document iterator set — builder().iterate(...)")
            sequences = [self.tokenizer_factory.create(d.content).get_tokens()
                         for d in self._docs]
            labels = [d.labels for d in self._docs]
        super().fit(sequences, labels)

    # ------------------------------------------------------------------ inference
    def infer_vector(self, text: str, steps: int = 10,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Train a fresh doc vector with frozen word weights (reference
        inferVector — label-aware inference)."""
        cache = self.vocab
        lt = self.lookup
        tokens = self.tokenizer_factory.create(text).get_tokens()
        idxs = [cache.index_of(t) for t in tokens]
        idxs = [i for i in idxs if i >= 0]
        rng = np.random.default_rng(self.seed)
        vec = jnp.asarray((rng.random(self.vector_length, ).astype(np.float32)
                           - 0.5) / self.vector_length)
        if not idxs:
            return np.asarray(vec)

        max_code = max((len(cache.word_at(i).code) for i in idxs), default=1) or 1
        pts = np.zeros((len(idxs), max_code), np.int32)
        codes = np.zeros((len(idxs), max_code), np.float32)
        mask = np.zeros((len(idxs), max_code), np.float32)
        for r, i in enumerate(idxs):
            vw = cache.word_at(i)
            L = min(len(vw.code), max_code)
            pts[r, :L] = vw.points[:L]
            codes[r, :L] = vw.code[:L]
            mask[r, :L] = 1.0
        pts_j, codes_j, mask_j = jnp.asarray(pts), jnp.asarray(codes), jnp.asarray(mask)
        syn1 = lt.syn1 if lt.syn1 is not None else lt.syn1neg

        @jax.jit
        def infer_step(v, lr):
            p_vecs = syn1[pts_j]                     # (N, L, D)
            f = jax.nn.sigmoid(jnp.einsum("d,nld->nl", v, p_vecs))
            g = (1.0 - codes_j - f) * lr * mask_j
            return v + jnp.einsum("nl,nld->d", g, p_vecs)

        for s in range(steps):
            lr = learning_rate * (1 - s / steps)
            vec = infer_step(vec, jnp.float32(max(lr, 1e-4)))
        return np.asarray(vec)
