"""Training-stats storage.

Reference: deeplearning4j-core api/storage/{StatsStorage,StatsStorageRouter,
StatsStorageListener,Persistable}.java — a Persistable record is keyed by
(sessionID, typeID, workerID, timestamp); storage backends are in-memory
(ui-model InMemoryStatsStorage), MapDB/SQLite files (FileStatsStorage /
J7FileStatsStorage). Here: in-memory dict store + a stdlib-sqlite3 file store
sharing one API; listeners receive post events.
"""
from __future__ import annotations

import sqlite3
import threading
from typing import Callable, Dict, List, Optional, Tuple


class Persistable:
    """Binary-encodable record (reference api/storage/Persistable.java)."""

    def get_session_id(self) -> str:
        raise NotImplementedError

    def get_type_id(self) -> str:
        raise NotImplementedError

    def get_worker_id(self) -> str:
        raise NotImplementedError

    def get_timestamp(self) -> int:
        raise NotImplementedError

    def encode(self) -> bytes:
        raise NotImplementedError

    @classmethod
    def decode(cls, data: bytes) -> "Persistable":
        raise NotImplementedError


class StatsStorageEvent:
    def __init__(self, kind: str, session_id: str, type_id: str, worker_id: str,
                 timestamp: int):
        self.kind = kind  # NewSessionID / NewTypeID / NewWorkerID / PostStaticInfo / PostUpdate
        self.session_id = session_id
        self.type_id = type_id
        self.worker_id = worker_id
        self.timestamp = timestamp


class StatsStorageRouter:
    """Write-side interface (reference StatsStorageRouter.java)."""

    def put_static_info(self, record: Persistable) -> None:
        raise NotImplementedError

    def put_update(self, record: Persistable) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write+listen (reference StatsStorage.java)."""

    def __init__(self):
        self._listeners: List[Callable[[StatsStorageEvent], None]] = []

    # ------------------------------------------------------------------ listeners
    def register_stats_storage_listener(self, listener) -> None:
        self._listeners.append(listener)

    def deregister_stats_storage_listener(self, listener) -> None:
        self._listeners.remove(listener)

    def _notify(self, event: StatsStorageEvent) -> None:
        for cb in self._listeners:
            cb(event)

    # ------------------------------------------------------------------ read API
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str, timestamp: int) -> List[bytes]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_num_updates(self, session_id: str, type_id: str,
                        worker_id: str) -> int:
        return len(self.get_all_updates_after(session_id, type_id, worker_id, -1))


class InMemoryStatsStorage(StatsStorage):
    """reference ui-model storage/InMemoryStatsStorage.java"""

    def __init__(self):
        super().__init__()
        self._static: Dict[Tuple[str, str, str], bytes] = {}
        self._updates: Dict[Tuple[str, str, str], List[Tuple[int, bytes]]] = {}
        self._lock = threading.Lock()

    def put_static_info(self, record: Persistable) -> None:
        key = (record.get_session_id(), record.get_type_id(), record.get_worker_id())
        with self._lock:
            new_session = key[0] not in {k[0] for k in
                                         list(self._static) + list(self._updates)}
            self._static[key] = record.encode()
        if new_session:
            self._notify(StatsStorageEvent("NewSessionID", *key, record.get_timestamp()))
        self._notify(StatsStorageEvent("PostStaticInfo", *key, record.get_timestamp()))

    def put_update(self, record: Persistable) -> None:
        key = (record.get_session_id(), record.get_type_id(), record.get_worker_id())
        with self._lock:
            self._updates.setdefault(key, []).append(
                (record.get_timestamp(), record.encode()))
        self._notify(StatsStorageEvent("PostUpdate", *key, record.get_timestamp()))

    def list_session_ids(self) -> List[str]:
        return sorted({k[0] for k in list(self._static) + list(self._updates)})

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        return sorted({k[1] for k in list(self._static) + list(self._updates)
                       if k[0] == session_id})

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        return sorted({k[2] for k in list(self._static) + list(self._updates)
                       if k[0] == session_id})

    def get_all_updates_after(self, session_id: str, type_id: str, worker_id: str,
                              timestamp: int) -> List[bytes]:
        rows = self._updates.get((session_id, type_id, worker_id), [])
        return [b for ts, b in rows if ts > timestamp]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[bytes]:
        rows = self._updates.get((session_id, type_id, worker_id), [])
        return rows[-1][1] if rows else None

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[bytes]:
        return self._static.get((session_id, type_id, worker_id))


class FileStatsStorage(StatsStorage):
    """Durable single-file storage over stdlib sqlite3 (reference
    J7FileStatsStorage.java, which is also SQLite-backed)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._conn:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS static_info ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, ts INTEGER, "
                "data BLOB, PRIMARY KEY (session_id, type_id, worker_id))")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS updates ("
                "session_id TEXT, type_id TEXT, worker_id TEXT, ts INTEGER, "
                "data BLOB)")

    def close(self) -> None:
        self._conn.close()

    def put_static_info(self, record: Persistable) -> None:
        key = (record.get_session_id(), record.get_type_id(), record.get_worker_id())
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?,?,?,?,?)",
                (*key, record.get_timestamp(), record.encode()))
        self._notify(StatsStorageEvent("PostStaticInfo", *key, record.get_timestamp()))

    def put_update(self, record: Persistable) -> None:
        key = (record.get_session_id(), record.get_type_id(), record.get_worker_id())
        with self._lock, self._conn:
            self._conn.execute("INSERT INTO updates VALUES (?,?,?,?,?)",
                               (*key, record.get_timestamp(), record.encode()))
        self._notify(StatsStorageEvent("PostUpdate", *key, record.get_timestamp()))

    def list_session_ids(self) -> List[str]:
        cur = self._conn.execute(
            "SELECT DISTINCT session_id FROM updates "
            "UNION SELECT DISTINCT session_id FROM static_info")
        return sorted(r[0] for r in cur.fetchall())

    def list_type_ids_for_session(self, session_id: str) -> List[str]:
        cur = self._conn.execute(
            "SELECT DISTINCT type_id FROM updates WHERE session_id=? "
            "UNION SELECT DISTINCT type_id FROM static_info WHERE session_id=?",
            (session_id, session_id))
        return sorted(r[0] for r in cur.fetchall())

    def list_worker_ids_for_session(self, session_id: str) -> List[str]:
        cur = self._conn.execute(
            "SELECT DISTINCT worker_id FROM updates WHERE session_id=? "
            "UNION SELECT DISTINCT worker_id FROM static_info WHERE session_id=?",
            (session_id, session_id))
        return sorted(r[0] for r in cur.fetchall())

    def get_all_updates_after(self, session_id: str, type_id: str, worker_id: str,
                              timestamp: int) -> List[bytes]:
        cur = self._conn.execute(
            "SELECT data FROM updates WHERE session_id=? AND type_id=? AND "
            "worker_id=? AND ts>? ORDER BY ts", (session_id, type_id, worker_id,
                                                 timestamp))
        return [r[0] for r in cur.fetchall()]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[bytes]:
        cur = self._conn.execute(
            "SELECT data FROM updates WHERE session_id=? AND type_id=? AND "
            "worker_id=? ORDER BY ts DESC LIMIT 1", (session_id, type_id, worker_id))
        row = cur.fetchone()
        return row[0] if row else None

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[bytes]:
        cur = self._conn.execute(
            "SELECT data FROM static_info WHERE session_id=? AND type_id=? AND "
            "worker_id=?", (session_id, type_id, worker_id))
        row = cur.fetchone()
        return row[0] if row else None
