from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage, InMemoryStatsStorage, StatsStorage,
)

__all__ = ["UIServer", "StatsListener", "StatsReport", "StatsStorage",
           "InMemoryStatsStorage", "FileStatsStorage"]
