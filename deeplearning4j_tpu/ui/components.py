"""Declarative report components rendered to standalone HTML (inline SVG).

Reference: deeplearning4j-ui-parent ui-components (SURVEY.md §2.9) — chart/
table/text components rendered to JS(d3), used for standalone HTML reports
(EvaluationTools ROC export, spark stats HTML). Here components render to
self-contained HTML with inline SVG — no JS dependency — following the
dataviz method: categorical hues in fixed validated order (slots below are
the documented reference palette, adjacent-pairs CVD-safe light+dark), 2px
line marks, recessive grid, legend for >=2 series, native tooltips via
<title>, text in ink tokens never series colors.
"""
from __future__ import annotations

import html as _html
from typing import List, Optional, Sequence

# Reference palette (validated fixed order; see dataviz references/palette.md)
SERIES_LIGHT = ["#2a78d6", "#eb6834", "#1baf7a", "#eda100",
                "#e87ba4", "#008300", "#4a3aa7", "#e34948"]
_CSS = """
.viz-root { color-scheme: light; font-family: system-ui, sans-serif;
  --surface-1: #fcfcfb; --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #e4e3df; background: var(--surface-1); color: var(--text-primary);
  padding: 16px; }
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root { color-scheme: dark;
    --surface-1: #1a1a19; --text-primary: #ffffff;
    --text-secondary: #c3c2b7; --grid: #3a3935; } }
.viz-root h2 { font-size: 15px; font-weight: 600; margin: 18px 0 6px; }
.viz-root table { border-collapse: collapse; font-size: 12px; }
.viz-root td, .viz-root th { border: 1px solid var(--grid); padding: 4px 10px;
  text-align: left; }
.viz-root th { color: var(--text-secondary); font-weight: 600; }
.viz-legend { font-size: 12px; color: var(--text-secondary);
  margin: 4px 0 10px; }
.viz-legend span.swatch { display: inline-block; width: 10px; height: 10px;
  border-radius: 2px; margin: 0 4px 0 12px; }
"""


class Component:
    def render(self) -> str:
        raise NotImplementedError


class ComponentText(Component):
    def __init__(self, text: str, heading: bool = False):
        self.text = text
        self.heading = heading

    def render(self) -> str:
        tag = "h2" if self.heading else "p"
        return f"<{tag}>{_html.escape(self.text)}</{tag}>"


class ComponentTable(Component):
    def __init__(self, header: Sequence[str], rows: Sequence[Sequence],
                 title: Optional[str] = None):
        self.header = list(header)
        self.rows = [list(r) for r in rows]
        self.title = title

    def render(self) -> str:
        out = []
        if self.title:
            out.append(f"<h2>{_html.escape(self.title)}</h2>")
        out.append("<table><tr>")
        out += [f"<th>{_html.escape(str(h))}</th>" for h in self.header]
        out.append("</tr>")
        for r in self.rows:
            out.append("<tr>" + "".join(
                f"<td>{_html.escape(_fmt(v))}</td>" for v in r) + "</tr>")
        out.append("</table>")
        return "".join(out)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


class _Chart(Component):
    W, H = 560, 300
    ML, MR, MT, MB = 56, 16, 16, 40

    def __init__(self, title: str, x_label: str = "", y_label: str = ""):
        self.title = title
        self.x_label = x_label
        self.y_label = y_label

    def _frame(self, body: str, legend: List[str], x_rng, y_rng) -> str:
        W, H, ML, MR, MT, MB = (self._geom())
        pw, ph = W - ML - MR, H - MT - MB
        grid, labels = [], []
        for i in range(5):
            fy = MT + ph * i / 4
            val = y_rng[1] - (y_rng[1] - y_rng[0]) * i / 4
            grid.append(f'<line x1="{ML}" y1="{fy:.1f}" x2="{W - MR}" '
                        f'y2="{fy:.1f}" stroke="var(--grid)" stroke-width="1"/>')
            labels.append(f'<text x="{ML - 6}" y="{fy + 4:.1f}" '
                          f'text-anchor="end" font-size="11" '
                          f'fill="var(--text-secondary)">{val:.3g}</text>')
        for i in range(5):
            fx = ML + pw * i / 4
            val = x_rng[0] + (x_rng[1] - x_rng[0]) * i / 4
            labels.append(f'<text x="{fx:.1f}" y="{H - MB + 16}" '
                          f'text-anchor="middle" font-size="11" '
                          f'fill="var(--text-secondary)">{val:.3g}</text>')
        if self.x_label:
            labels.append(f'<text x="{ML + pw / 2}" y="{H - 6}" '
                          f'text-anchor="middle" font-size="12" '
                          f'fill="var(--text-secondary)">'
                          f'{_html.escape(self.x_label)}</text>')
        if self.y_label:
            labels.append(f'<text x="14" y="{MT + ph / 2}" font-size="12" '
                          f'fill="var(--text-secondary)" text-anchor="middle" '
                          f'transform="rotate(-90 14 {MT + ph / 2})">'
                          f'{_html.escape(self.y_label)}</text>')
        leg = ""
        if len(legend) >= 2:
            leg = '<div class="viz-legend">' + "".join(
                f'<span class="swatch" style="background:{SERIES_LIGHT[i % 8]}">'
                f'</span>{_html.escape(n)}' for i, n in enumerate(legend)) + "</div>"
        return (f"<h2>{_html.escape(self.title)}</h2>{leg}"
                f'<svg viewBox="0 0 {W} {H}" width="{W}" height="{H}" '
                f'role="img" aria-label="{_html.escape(self.title)}">'
                + "".join(grid) + body + "".join(labels) + "</svg>")

    def _geom(self):
        return self.W, self.H, self.ML, self.MR, self.MT, self.MB

    @staticmethod
    def _ranges(xss, yss):
        xs = [x for s in xss for x in s]
        ys = [y for s in yss for y in s]
        x0, x1 = (min(xs), max(xs)) if xs else (0, 1)
        y0, y1 = (min(ys), max(ys)) if ys else (0, 1)
        if x1 == x0:
            x1 = x0 + 1
        if y1 == y0:
            y1 = y0 + 1
        return (x0, x1), (y0, y1)


class ChartLine(_Chart):
    """Multi-series line chart (reference ui-components ChartLine)."""

    def __init__(self, title: str, x_label: str = "", y_label: str = ""):
        super().__init__(title, x_label, y_label)
        self.series: List[tuple] = []

    def add_series(self, name: str, x: Sequence[float],
                   y: Sequence[float]) -> "ChartLine":
        self.series.append((name, list(x), list(y)))
        return self

    def render(self) -> str:
        W, H, ML, MR, MT, MB = self._geom()
        pw, ph = W - ML - MR, H - MT - MB
        x_rng, y_rng = self._ranges([s[1] for s in self.series],
                                    [s[2] for s in self.series])
        body = []
        for i, (name, xs, ys) in enumerate(self.series):
            pts = " ".join(
                f"{ML + (x - x_rng[0]) / (x_rng[1] - x_rng[0]) * pw:.1f},"
                f"{MT + ph - (y - y_rng[0]) / (y_rng[1] - y_rng[0]) * ph:.1f}"
                for x, y in zip(xs, ys))
            color = SERIES_LIGHT[i % 8]
            body.append(f'<polyline points="{pts}" fill="none" '
                        f'stroke="{color}" stroke-width="2">'
                        f"<title>{_html.escape(name)}</title></polyline>")
        return self._frame("".join(body), [s[0] for s in self.series],
                           x_rng, y_rng)


class ChartScatter(ChartLine):
    """Scatter (reference ChartScatter); series cap 3 per all-pairs rule."""

    def render(self) -> str:
        W, H, ML, MR, MT, MB = self._geom()
        pw, ph = W - ML - MR, H - MT - MB
        x_rng, y_rng = self._ranges([s[1] for s in self.series],
                                    [s[2] for s in self.series])
        body = []
        for i, (name, xs, ys) in enumerate(self.series[:3]):
            color = SERIES_LIGHT[i % 8]
            for x, y in zip(xs, ys):
                cx = ML + (x - x_rng[0]) / (x_rng[1] - x_rng[0]) * pw
                cy = MT + ph - (y - y_rng[0]) / (y_rng[1] - y_rng[0]) * ph
                body.append(f'<circle cx="{cx:.1f}" cy="{cy:.1f}" r="4" '
                            f'fill="{color}" stroke="var(--surface-1)" '
                            f'stroke-width="2"><title>'
                            f"{_html.escape(name)}: ({x:.4g}, {y:.4g})"
                            f"</title></circle>")
        return self._frame("".join(body), [s[0] for s in self.series[:3]],
                           x_rng, y_rng)


class ChartHistogram(_Chart):
    """Histogram (reference ChartHistogram): bin edges + counts."""

    def __init__(self, title: str, lower: Sequence[float],
                 upper: Sequence[float], counts: Sequence[float],
                 x_label: str = "", y_label: str = "count"):
        super().__init__(title, x_label, y_label)
        self.lower, self.upper = list(lower), list(upper)
        self.counts = list(counts)

    def render(self) -> str:
        W, H, ML, MR, MT, MB = self._geom()
        pw, ph = W - ML - MR, H - MT - MB
        x_rng = (min(self.lower), max(self.upper)) if self.lower else (0, 1)
        y_rng = (0, max(self.counts) or 1)
        body = []
        for lo, hi, c in zip(self.lower, self.upper, self.counts):
            x0 = ML + (lo - x_rng[0]) / (x_rng[1] - x_rng[0]) * pw
            x1 = ML + (hi - x_rng[0]) / (x_rng[1] - x_rng[0]) * pw
            bh = (c / y_rng[1]) * ph
            # 2px surface gap between adjacent bars; 4px rounded data end
            body.append(
                f'<rect x="{x0 + 1:.1f}" y="{MT + ph - bh:.1f}" '
                f'width="{max(x1 - x0 - 2, 1):.1f}" height="{bh:.1f}" '
                f'rx="4" fill="{SERIES_LIGHT[0]}">'
                f"<title>[{lo:.4g}, {hi:.4g}): {c:.4g}</title></rect>")
        return self._frame("".join(body), [], x_rng, y_rng)


class ComponentDiv(Component):
    def __init__(self, *children: Component):
        self.children = list(children)

    def render(self) -> str:
        return "<div>" + "".join(c.render() for c in self.children) + "</div>"


def render_page(title: str, *components: Component) -> str:
    """Standalone HTML document from components (reference ui-components
    rendering into an HTML file)."""
    body = "".join(c.render() for c in components)
    return (f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{_html.escape(title)}</title><style>{_CSS}</style></head>"
            f"<body><div class='viz-root'><h1 style='font-size:18px'>"
            f"{_html.escape(title)}</h1>{body}</div></body></html>")
