"""Training-UI internationalization.

Reference: deeplearning4j-play ui/i18n/DefaultI18N.java:1 — a singleton
message source resolving (key, language) to UI strings, with a default
language fallback and per-language resource tables (the reference loads
dl4j_i18n/*.properties files; the same tables are embedded here).

The server substitutes ``{{key}}`` placeholders in its page templates through
``I18N.get_message`` — the language comes from the request's ``?lang=``
query parameter or ``Accept-Language`` header, falling back to the instance
default (reference I18NProvider + language cookie handling).
"""
from __future__ import annotations

from typing import Dict, List, Optional

DEFAULT_LANGUAGE = "en"

#: key -> {language -> message}. English is complete; other languages fall
#: back to English per key (reference DefaultI18N.getMessage fallback).
_MESSAGES: Dict[str, Dict[str, str]] = {
    "train.pagetitle": {
        "en": "DL4J-TPU Training UI", "ja": "DL4J-TPU トレーニングUI",
        "de": "DL4J-TPU Trainings-UI", "fr": "Interface d'entraînement DL4J-TPU",
        "es": "Interfaz de entrenamiento DL4J-TPU", "zh": "DL4J-TPU 训练界面",
        "ko": "DL4J-TPU 학습 UI", "ru": "Интерфейс обучения DL4J-TPU",
    },
    "train.nav.overview": {
        "en": "Overview", "ja": "概要", "de": "Übersicht", "fr": "Aperçu",
        "es": "Resumen", "zh": "概览", "ko": "개요", "ru": "Обзор",
    },
    "train.nav.model": {
        "en": "Model", "ja": "モデル", "de": "Modell", "fr": "Modèle",
        "es": "Modelo", "zh": "模型", "ko": "모델", "ru": "Модель",
    },
    "train.nav.system": {
        "en": "System", "ja": "システム", "de": "System", "fr": "Système",
        "es": "Sistema", "zh": "系统", "ko": "시스템", "ru": "Система",
    },
    "train.nav.convolutional": {
        "en": "Convolutional", "ja": "畳み込み", "de": "Faltung",
        "fr": "Convolution", "es": "Convolución", "zh": "卷积",
        "ko": "합성곱", "ru": "Свёртка",
    },
    "train.nav.histograms": {
        "en": "Histograms", "ja": "ヒストグラム", "de": "Histogramme",
        "fr": "Histogrammes", "es": "Histogramas", "zh": "直方图",
        "ko": "히스토그램", "ru": "Гистограммы",
    },
    "train.overview.title": {
        "en": "Training overview", "ja": "トレーニング概要",
        "de": "Trainingsübersicht", "fr": "Aperçu de l'entraînement",
        "es": "Resumen del entrenamiento", "zh": "训练概览",
        "ko": "학습 개요", "ru": "Обзор обучения",
    },
    "train.overview.chart.score": {
        "en": "Model score vs iteration", "ja": "スコア対反復",
        "de": "Modellwert pro Iteration", "fr": "Score du modèle par itération",
        "es": "Puntuación del modelo por iteración", "zh": "模型得分与迭代",
        "ko": "반복별 모델 점수", "ru": "Оценка модели по итерациям",
    },
    "train.overview.chart.ratio": {
        "en": "Mean update:parameter ratio (log10)",
        "ja": "平均更新:パラメータ比 (log10)",
        "de": "Mittleres Update:Parameter-Verhältnis (log10)",
        "fr": "Ratio moyen mise à jour:paramètre (log10)",
        "es": "Razón media actualización:parámetro (log10)",
        "zh": "平均更新:参数比 (log10)", "ko": "평균 업데이트:파라미터 비율 (log10)",
        "ru": "Среднее отношение обновление:параметр (log10)",
    },
    "train.model.title": {
        "en": "Model", "ja": "モデル", "de": "Modell", "fr": "Modèle",
        "es": "Modelo", "zh": "模型", "ko": "모델", "ru": "Модель",
    },
    "train.model.graph": {
        "en": "Network graph", "ja": "ネットワークグラフ",
        "de": "Netzwerkgraph", "fr": "Graphe du réseau",
        "es": "Grafo de la red", "zh": "网络图", "ko": "네트워크 그래프",
        "ru": "Граф сети",
    },
    "train.model.layers": {
        "en": "Layers", "ja": "レイヤー", "de": "Schichten", "fr": "Couches",
        "es": "Capas", "zh": "层", "ko": "레이어", "ru": "Слои",
    },
    "train.model.histograms": {
        "en": "Parameter histograms (latest iteration)",
        "ja": "パラメータヒストグラム（最新の反復）",
        "de": "Parameterhistogramme (letzte Iteration)",
        "fr": "Histogrammes des paramètres (dernière itération)",
        "es": "Histogramas de parámetros (última iteración)",
        "zh": "参数直方图（最新迭代）", "ko": "파라미터 히스토그램 (최근 반복)",
        "ru": "Гистограммы параметров (последняя итерация)",
    },
    "train.model.table.parameter": {
        "en": "parameter", "ja": "パラメータ", "de": "Parameter",
        "fr": "paramètre", "es": "parámetro", "zh": "参数", "ko": "파라미터",
        "ru": "параметр",
    },
    "train.model.table.meanw": {
        "en": "mean |w|", "ja": "平均 |w|", "de": "Mittel |w|",
        "fr": "moyenne |w|", "es": "media |w|", "zh": "均值 |w|",
        "ko": "평균 |w|", "ru": "среднее |w|",
    },
    "train.model.table.meangrad": {
        "en": "mean |grad|", "ja": "平均 |grad|", "de": "Mittel |grad|",
        "fr": "moyenne |grad|", "es": "media |grad|", "zh": "均值 |grad|",
        "ko": "평균 |grad|", "ru": "среднее |grad|",
    },
    "train.system.title": {
        "en": "System", "ja": "システム", "de": "System", "fr": "Système",
        "es": "Sistema", "zh": "系统", "ko": "시스템", "ru": "Система",
    },
    "train.system.chart.rss": {
        "en": "Host RSS", "ja": "ホストRSS", "de": "Host-RSS",
        "fr": "RSS hôte", "es": "RSS del host", "zh": "主机 RSS",
        "ko": "호스트 RSS", "ru": "RSS хоста",
    },
    "train.system.chart.device": {
        "en": "Device memory", "ja": "デバイスメモリ",
        "de": "Gerätespeicher", "fr": "Mémoire du périphérique",
        "es": "Memoria del dispositivo", "zh": "设备内存",
        "ko": "디바이스 메모리", "ru": "Память устройства",
    },
    "train.conv.title": {
        "en": "Convolutional activations", "ja": "畳み込み活性",
        "de": "Faltungsaktivierungen", "fr": "Activations convolutives",
        "es": "Activaciones convolucionales", "zh": "卷积激活",
        "ko": "합성곱 활성화", "ru": "Свёрточные активации",
    },
    "train.histograms.params": {
        "en": "Parameters", "ja": "パラメータ", "de": "Parameter",
        "fr": "Paramètres", "es": "Parámetros", "zh": "参数",
        "ko": "파라미터", "ru": "Параметры",
    },
    "train.histograms.gradients": {
        "en": "Gradients", "ja": "勾配", "de": "Gradienten",
        "fr": "Gradients", "es": "Gradientes", "zh": "梯度", "ko": "그래디언트",
        "ru": "Градиенты",
    },
    "train.histograms.updates": {
        "en": "Updates", "ja": "更新", "de": "Updates",
        "fr": "Mises à jour", "es": "Actualizaciones", "zh": "更新",
        "ko": "업데이트", "ru": "Обновления",
    },
    "train.histograms.none": {
        "en": "no statistics recorded yet", "ja": "統計はまだ記録されていません",
        "de": "noch keine Statistiken aufgezeichnet",
        "fr": "aucune statistique enregistrée",
        "es": "aún no hay estadísticas registradas", "zh": "尚未记录统计数据",
        "ko": "아직 기록된 통계가 없습니다", "ru": "статистика ещё не записана",
    },
}


class I18N:
    """Singleton message source (reference DefaultI18N.getInstance)."""

    _instance: Optional["I18N"] = None

    def __init__(self):
        self.default_language = DEFAULT_LANGUAGE

    @classmethod
    def get_instance(cls) -> "I18N":
        if cls._instance is None:
            cls._instance = I18N()
        return cls._instance

    def set_default_language(self, lang: str) -> None:
        self.default_language = lang

    @staticmethod
    def available_languages() -> List[str]:
        langs = set()
        for table in _MESSAGES.values():
            langs.update(table)
        return sorted(langs)

    def get_message(self, key: str, lang: Optional[str] = None) -> str:
        """Resolve key in ``lang`` with English fallback; unknown keys echo
        the key (the reference returns the raw key too — a visible marker
        beats a 500)."""
        table = _MESSAGES.get(key)
        if table is None:
            return key
        lang = (lang or self.default_language).split("-")[0].lower()
        return table.get(lang) or table.get("en") or key

    def get_messages(self, lang: str) -> Dict[str, str]:
        return {k: self.get_message(k, lang) for k in _MESSAGES}
