"""Training UI web server.

Reference: deeplearning4j-play ui/play/PlayUIServer.java — a web server with
pluggable modules (TrainModule overview/model/system pages,
RemoteReceiverModule POST endpoint) attached to StatsStorage instances via
listeners. Here: stdlib http.server in a daemon thread serving JSON endpoints
plus one self-contained HTML page (inline canvas charts, no external assets —
the environment has zero egress), and the remote-receiver POST route.
"""
from __future__ import annotations

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import StatsStorage

log = logging.getLogger(__name__)

_STYLE = """<style>
body { font-family: sans-serif; margin: 20px; background: #fafafa; }
h2 { color: #333; } .chart { background: #fff; border: 1px solid #ddd;
margin-bottom: 16px; padding: 8px; }
nav a { margin-right: 14px; color: #36c; text-decoration: none; }
table { border-collapse: collapse; background: #fff; }
td, th { border: 1px solid #ddd; padding: 4px 10px; font-size: 13px; }
</style>"""

_NAV = """<nav><a href="/train/overview">{{train.nav.overview}}</a>
<a href="/train/model">{{train.nav.model}}</a>
<a href="/train/system">{{train.nav.system}}</a>
<a href="/train/convolutional">{{train.nav.convolutional}}</a>
<a href="/train/histograms">{{train.nav.histograms}}</a></nav>"""

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{{train.pagetitle}}</title>
""" + _STYLE + """</head>
<body>
""" + _NAV + """
<h2>{{train.overview.title}}</h2>
<div class="chart"><canvas id="score" width="900" height="260"></canvas></div>
<div class="chart"><canvas id="ratio" width="900" height="260"></canvas></div>
<script>
function drawSeries(canvasId, xs, ys, label, color) {
  const c = document.getElementById(canvasId), ctx = c.getContext('2d');
  ctx.clearRect(0, 0, c.width, c.height);
  if (!ys.length) return;
  const ymin = Math.min(...ys), ymax = Math.max(...ys), pad = 36;
  const sx = (c.width - 2*pad) / Math.max(xs.length - 1, 1);
  const sy = (c.height - 2*pad) / Math.max(ymax - ymin, 1e-9);
  ctx.strokeStyle = '#999'; ctx.strokeRect(pad, pad, c.width-2*pad, c.height-2*pad);
  ctx.fillStyle = '#333'; ctx.fillText(label + ' (last: ' +
      ys[ys.length-1].toPrecision(5) + ')', pad, pad - 6);
  ctx.strokeStyle = color; ctx.beginPath();
  ys.forEach((y, i) => { const px = pad + i*sx,
      py = c.height - pad - (y - ymin)*sy;
      i ? ctx.lineTo(px, py) : ctx.moveTo(px, py); });
  ctx.stroke();
}
async function refresh() {
  const r = await fetch('/train/overview/data'); const d = await r.json();
  drawSeries('score', d.iterations, d.scores, '{{js:train.overview.chart.score}}', '#c33');
  drawSeries('ratio', d.iterations, d.updateRatios,
             '{{js:train.overview.chart.ratio}}', '#36c');
}
refresh(); setInterval(refresh, 2000);
</script>
</body></html>
"""

# Rendered model page: network flow graph (FlowModule equivalent) + per-layer
# parameter tables and histograms (reference TrainModule model tab,
# deeplearning4j-play TrainModule.java; FlowIterationListener flow chart).
_MODEL_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{{train.pagetitle}} - {{train.model.title}}</title>
""" + _STYLE + """</head>
<body>
""" + _NAV + """
<h2>{{train.model.title}}</h2>
<div class="chart"><b>{{train.model.graph}}</b><br>
<canvas id="flow" width="900" height="220"></canvas></div>
<div class="chart"><b>{{train.model.layers}}</b><div id="layers"></div></div>
<div class="chart"><b>{{train.model.histograms}}</b>
<div id="hists"></div></div>
<script>
function drawFlow(graph) {
  const c = document.getElementById('flow'), ctx = c.getContext('2d');
  ctx.clearRect(0, 0, c.width, c.height);
  const nodes = graph.nodes || [];
  if (!nodes.length) { ctx.fillText('no model attached', 20, 30); return; }
  // simple layered layout: x by topological index, y staggered
  const xy = {}, w = 120, h = 36;
  const sx = Math.min(150, (c.width - w - 20) / Math.max(nodes.length - 1, 1));
  nodes.forEach((n, i) => { xy[n.name] = [10 + i * sx,
                                          30 + (i % 3) * 60]; });
  ctx.strokeStyle = '#999';
  (graph.edges || []).forEach(e => {
    const a = xy[e[0]], b = xy[e[1]];
    if (!a || !b) return;
    ctx.beginPath(); ctx.moveTo(a[0] + w, a[1] + h / 2);
    ctx.lineTo(b[0], b[1] + h / 2); ctx.stroke();
  });
  nodes.forEach(n => {
    const [x, y] = xy[n.name];
    ctx.fillStyle = n.type === 'input' ? '#def' : '#fff';
    ctx.fillRect(x, y, w, h); ctx.strokeRect(x, y, w, h);
    ctx.fillStyle = '#333';
    ctx.fillText(n.name, x + 6, y + 14);
    ctx.fillText(n.type + (n.nParams ? ' (' + n.nParams + ')' : ''),
                 x + 6, y + 28);
  });
}
function bar(bins, lo, hi) {
  const cv = document.createElement('canvas');
  cv.width = 260; cv.height = 80;
  const ctx = cv.getContext('2d'), m = Math.max(...bins, 1);
  const bw = (cv.width - 10) / bins.length;
  ctx.fillStyle = '#36c';
  bins.forEach((b, i) => {
    const bh = (cv.height - 20) * b / m;
    ctx.fillRect(5 + i * bw, cv.height - 15 - bh, bw - 1, bh);
  });
  ctx.fillStyle = '#333';
  ctx.fillText(lo.toPrecision(3), 4, cv.height - 3);
  ctx.fillText(hi.toPrecision(3), cv.width - 50, cv.height - 3);
  return cv;
}
async function refresh() {
  const g = await (await fetch('/train/model/graph')).json();
  drawFlow(g);
  const d = await (await fetch('/train/model/data')).json();
  let html = '<table><tr><th>{{js:train.model.table.parameter}}</th>' +
             '<th>{{js:train.model.table.meanw}}</th>' +
             '<th>{{js:train.model.table.meangrad}}</th></tr>';
  for (const [name, v] of Object.entries(d.layers || {})) {
    const gm = (d.gradients || {})[name];
    html += '<tr><td>' + name + '</td><td>' + v.meanMagnitude.toPrecision(4)
         + '</td><td>' + (gm ? gm.meanMagnitude.toPrecision(4) : '-')
         + '</td></tr>';
  }
  document.getElementById('layers').innerHTML = html + '</table>';
  const hs = await (await fetch('/train/histograms/data')).json();
  const hd = document.getElementById('hists');
  hd.innerHTML = '';
  for (const [name, v] of Object.entries(hs.params || {})) {
    const div = document.createElement('div');
    div.style.display = 'inline-block'; div.style.margin = '6px';
    div.appendChild(document.createTextNode(name));
    div.appendChild(document.createElement('br'));
    div.appendChild(bar(v.bins, v.min, v.max));
    hd.appendChild(div);
  }
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""

# Rendered system page (reference TrainModule system tab: memory charts).
_SYSTEM_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{{train.pagetitle}} - {{train.system.title}}</title>
""" + _STYLE + """</head>
<body>
""" + _NAV + """
<h2>{{train.system.title}}</h2>
<div class="chart"><canvas id="rss" width="900" height="240"></canvas></div>
<div class="chart"><canvas id="dev" width="900" height="240"></canvas></div>
<script>
function drawSeries(canvasId, ys, label, color) {
  const c = document.getElementById(canvasId), ctx = c.getContext('2d');
  ctx.clearRect(0, 0, c.width, c.height);
  if (!ys.length) { ctx.fillText(label + ': no data', 20, 30); return; }
  const ymin = Math.min(...ys), ymax = Math.max(...ys), pad = 36;
  const sx = (c.width - 2*pad) / Math.max(ys.length - 1, 1);
  const sy = (c.height - 2*pad) / Math.max(ymax - ymin, 1e-9);
  ctx.strokeStyle = '#999';
  ctx.strokeRect(pad, pad, c.width-2*pad, c.height-2*pad);
  ctx.fillStyle = '#333';
  ctx.fillText(label + ' (last: ' + (ys[ys.length-1]/1048576).toFixed(1)
               + ' MB)', pad, pad - 6);
  ctx.strokeStyle = color; ctx.beginPath();
  ys.forEach((y, i) => { const px = pad + i*sx,
      py = c.height - pad - (y - ymin)*sy;
      i ? ctx.lineTo(px, py) : ctx.moveTo(px, py); });
  ctx.stroke();
}
async function refresh() {
  const d = await (await fetch('/train/system/data')).json();
  drawSeries('rss', d.memRssBytes, '{{js:train.system.chart.rss}}', '#c33');
  drawSeries('dev', d.deviceMemBytes, '{{js:train.system.chart.device}}', '#36c');
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""

# Convolutional module (reference ConvolutionalListenerModule +
# ConvolutionalIterationListener: streams conv-layer activation images).
_CONV_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{{train.pagetitle}} - {{train.nav.convolutional}}</title>
""" + _STYLE + """</head>
<body>
""" + _NAV + """
<h2>{{train.conv.title}}</h2>
<div id="meta"></div><div id="maps"></div>
<script>
function heat(arr) {
  const hgt = arr.length, wid = arr[0].length, scale = 4;
  const cv = document.createElement('canvas');
  cv.width = wid * scale; cv.height = hgt * scale;
  const ctx = cv.getContext('2d');
  let lo = Infinity, hi = -Infinity;
  arr.forEach(r => r.forEach(v => { lo = Math.min(lo, v);
                                    hi = Math.max(hi, v); }));
  const span = Math.max(hi - lo, 1e-9);
  arr.forEach((row, y) => row.forEach((v, x) => {
    const t = Math.floor(255 * (v - lo) / span);
    ctx.fillStyle = 'rgb(' + t + ',' + t + ',' + (255 - t) + ')';
    ctx.fillRect(x * scale, y * scale, scale, scale);
  }));
  return cv;
}
async function refresh() {
  const d = await (await fetch('/train/convolutional/data')).json();
  document.getElementById('meta').textContent =
      d.maps && d.maps.length ? 'iteration ' + d.iteration
                              : 'no activations posted yet';
  const md = document.getElementById('maps');
  md.innerHTML = '';
  (d.maps || []).forEach(m => {
    const div = document.createElement('div'); div.className = 'chart';
    div.appendChild(document.createTextNode(m.layer));
    div.appendChild(document.createElement('br'));
    m.channels.forEach(ch => { const cv = heat(ch);
      cv.style.marginRight = '4px'; div.appendChild(cv); });
    md.appendChild(div);
  });
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""


_PLACEHOLDER = re.compile(r"\{\{(js:)?([A-Za-z0-9_.]+)\}\}")


def _localize(template: str, lang: Optional[str]) -> str:
    """Substitute {{key}} placeholders through the I18N message source
    (reference DefaultI18N.getMessage over the Play templates).

    ``{{js:key}}`` escapes the message for a single-quoted JavaScript
    string literal (translations legitimately contain apostrophes — e.g.
    the French page title — and must not break the inline scripts)."""
    from deeplearning4j_tpu.ui.i18n import I18N

    i18n = I18N.get_instance()

    def sub(m):
        msg = i18n.get_message(m.group(2), lang)
        if m.group(1):  # js context
            return (json.dumps(msg)[1:-1]          # \-escapes, control chars
                    .replace("'", "\\'").replace("</", "<\\/"))
        return msg

    return _PLACEHOLDER.sub(sub, template)


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTPUUIServer/1.0"
    ui: "UIServer" = None

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _request_lang(self) -> Optional[str]:
        """?lang= query param, else the Accept-Language header's first tag
        (reference I18NProvider language resolution)."""
        q = parse_qs(urlparse(self.path).query)
        if q.get("lang"):
            return q["lang"][0]
        accept = self.headers.get("Accept-Language")
        if accept:
            # first tag, q-value stripped: "ja;q=0.9, en;q=0.8" -> "ja"
            return accept.split(",")[0].split(";")[0].strip()
        return None

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page: str) -> None:
        body = page.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, text: str, content_type: str = "text/plain") -> None:
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = urlparse(self.path).path
        lang = self._request_lang()
        if path in ("/", "/train", "/train/overview"):
            self._html(_localize(_PAGE, lang))
        elif path == "/train/model":
            self._html(_localize(_MODEL_PAGE, lang))
        elif path == "/train/system":
            self._html(_localize(_SYSTEM_PAGE, lang))
        elif path == "/train/convolutional":
            self._html(_localize(_CONV_PAGE, lang))
        elif path == "/train/histograms":
            # server-side rendered histogram page built from ui-components
            # charts (reference HistogramModule rendered view)
            q = parse_qs(urlparse(self.path).query)
            self._html(self.ui.histograms_page(q.get("session", [None])[0],
                                               lang))
        elif path == "/lang/setCurrent":
            # reference DefaultI18N: change the server's default language
            q = parse_qs(urlparse(self.path).query)
            from deeplearning4j_tpu.ui.i18n import I18N
            I18N.get_instance().set_default_language(
                q.get("lang", ["en"])[0])
            self._json({"status": "ok"})
        elif path == "/train/model/graph":
            self._json(self.ui.model_graph())
        elif path == "/train/convolutional/data":
            self._json(self.ui.conv_data())
        elif path == "/train/overview/data":
            self._json(self.ui.overview_data())
        elif path == "/train/sessions":
            self._json(self.ui.sessions())
        elif path == "/train/model/data":
            q = parse_qs(urlparse(self.path).query)
            self._json(self.ui.model_data(q.get("session", [None])[0]))
        elif path == "/train/system/data":
            self._json(self.ui.system_data())
        elif path == "/metrics":
            # Prometheus text exposition of the process-global registry
            # (version 0.0.4 is what prometheus scrapers negotiate)
            self._text(self.ui.metrics_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/train/telemetry/data":
            self._json(self.ui.telemetry_data())
        elif path == "/train/health":
            self._json(self.ui.health_data())
        elif path == "/serve/status":
            # serving engine pane: models/versions, queue depth, bucket
            # occupancy — same payload the InferenceServer exposes itself
            self._json(self.ui.serve_status_data())
        elif path == "/serve/traces":
            # tail-sampled request traces (newest first; ?trace= resolves
            # one full span tree) — same payload as the InferenceServer's
            q = parse_qs(urlparse(self.path).query)
            trace_id = q.get("trace", [None])[0]
            if trace_id:
                tree = self.ui.serve_trace(trace_id)
                self._json(tree if tree is not None
                           else {"error": "not found"},
                           200 if tree is not None else 404)
            else:
                self._json(self.ui.serve_traces())
        elif path == "/serve/slo":
            self._json(self.ui.serve_slo())
        elif path == "/fleet/metrics":
            # the FEDERATED exposition: every member's series merged, vs
            # /metrics which is this process's registry only
            self._text(self.ui.fleet_metrics_text(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/fleet/status":
            self._json(self.ui.fleet_status_data())
        elif path == "/train/health/bundles":
            self._json(self.ui.health_bundles())
        elif path == "/train/profiles":
            # persistent trace-capture index (observability/profiler.py)
            self._json(self.ui.profiles())
        elif path == "/train/profiles/summary":
            # per-trace attribution download; ?trace= must equal an indexed
            # logdir verbatim (the index is the allow-list — no path math on
            # request input, so no traversal)
            q = parse_qs(urlparse(self.path).query)
            self._json(self.ui.profile_summary(q.get("trace", [None])[0]))
        elif path == "/train/histograms/data":
            # HistogramModule equivalent: latest param/gradient/update
            # histograms per variable
            q = parse_qs(urlparse(self.path).query)
            self._json(self.ui.histogram_data(q.get("session", [None])[0]))
        elif path == "/tsne/data":
            # TsneModule equivalent: last uploaded embedding coords
            self._json(self.ui.tsne_data())
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        path = urlparse(self.path).path
        if path == "/train/convolutional/upload":
            length = int(self.headers.get("Content-Length", "0"))
            try:
                self.ui.set_conv_data(json.loads(self.rfile.read(length)))
            except Exception as e:
                self._json({"status": "error", "detail": str(e)}, 400)
                return
            self._json({"status": "ok"})
        elif path == "/tsne/upload":
            # TsneModule upload: JSON {"coords": [[x, y], ...], "labels": []}
            length = int(self.headers.get("Content-Length", "0"))
            try:
                payload = json.loads(self.rfile.read(length))
                self.ui.set_tsne(payload)
            except Exception as e:
                self._json({"status": "error", "detail": str(e)}, 400)
                return
            self._json({"status": "ok"})
        elif path == "/remoteReceive":
            # RemoteReceiverModule equivalent: accept encoded StatsReports
            length = int(self.headers.get("Content-Length", "0"))
            data = self.rfile.read(length)
            try:
                report = StatsReport.decode(data)
            except Exception as e:
                self._json({"status": "error", "detail": str(e)}, 400)
                return
            self.ui.post_remote(report)
            self._json({"status": "ok"})
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """reference UIServer.getInstance() + attach(statsStorage)"""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self._storages: List[StatsStorage] = []
        self._remote_storage: Optional[StatsStorage] = None
        self._tsne: dict = {"coords": [], "labels": []}
        handler = type("BoundHandler", (_Handler,), {"ui": self})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        self._storages.remove(storage)

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None) -> None:
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        self._remote_storage = storage or InMemoryStatsStorage()
        self.attach(self._remote_storage)

    def post_remote(self, report: StatsReport) -> None:
        if self._remote_storage is None:
            self.enable_remote_listener()
        self._remote_storage.put_update(report)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None

    # ------------------------------------------------------------------ data API
    def _all_reports(self, session: Optional[str] = None) -> List[StatsReport]:
        out: List[StatsReport] = []
        for storage in self._storages:
            for sid in storage.list_session_ids():
                if session and sid != session:
                    continue
                for wid in storage.list_worker_ids_for_session(sid):
                    for blob in storage.get_all_updates_after(
                            sid, StatsReport.TYPE_ID, wid, -1):
                        try:
                            out.append(StatsReport.decode(blob))
                        except ValueError:
                            log.debug("skipping undecodable stats blob for "
                                      "session %s worker %s", sid, wid,
                                      exc_info=True)
        out.sort(key=lambda r: (r.timestamp, r.iteration))
        return out

    def sessions(self) -> List[str]:
        out: List[str] = []
        for storage in self._storages:
            out.extend(storage.list_session_ids())
        return sorted(set(out))

    def overview_data(self) -> dict:
        reports = self._all_reports()
        import math

        ratios = []
        for r in reports:
            pairs = [(r.update_stats[k][0], r.param_stats[k][0])
                     for k in r.update_stats if k in r.param_stats]
            vals = [u / p for u, p in pairs if p > 0 and u > 0]
            ratios.append(math.log10(sum(vals) / len(vals)) if vals else -10.0)
        return {
            "iterations": [r.iteration for r in reports],
            "scores": [r.score for r in reports],
            "updateRatios": ratios,
            "iterationTimesMs": [r.iteration_time_ms for r in reports],
        }

    def model_data(self, session: Optional[str] = None) -> dict:
        reports = self._all_reports(session)
        if not reports:
            return {"layers": {}}
        last = reports[-1]
        return {
            "layers": {
                name: {"meanMagnitude": mm, "histogram": hist,
                       "range": list(rng)}
                for name, (mm, hist, rng) in last.param_stats.items()
            },
            "gradients": {
                name: {"meanMagnitude": mm}
                for name, (mm, _, _) in last.gradient_stats.items()
            },
        }

    def system_data(self) -> dict:
        reports = self._all_reports()
        return {
            "memRssBytes": [r.mem_rss_bytes for r in reports],
            "deviceMemBytes": [r.device_mem_bytes for r in reports],
            "timestamps": [r.timestamp for r in reports],
        }

    def metrics_text(self) -> str:
        """Prometheus text for ``/metrics``: the process-global registry
        that the fit loops, compile tracker, and spans write into."""
        from deeplearning4j_tpu.observability import global_registry

        return global_registry().prometheus_text()

    def fleet_metrics_text(self) -> str:
        """Federated Prometheus text for ``/fleet/metrics``: every fleet
        member's series merged by the installed FederatedRegistry (falls
        back to an honest single-member view when none is installed)."""
        from deeplearning4j_tpu.observability.federation import \
            fleet_metrics_text

        return fleet_metrics_text()

    def fleet_status_data(self) -> dict:
        """Fleet roster + registered status providers for
        ``/fleet/status``."""
        from deeplearning4j_tpu.observability.federation import fleet_status

        return fleet_status()

    def serve_status_data(self) -> dict:
        """Serving-engine snapshot for ``/serve/status``: loaded model
        versions, queue depth, bucket occupancy — training health and
        serving share one pane (lazy import: the UI must not pull the
        serving stack unless something asks for it)."""
        from deeplearning4j_tpu.keras_server.serving import serve_status

        return serve_status()

    def serve_traces(self) -> dict:
        """Newest-first kept-trace summaries for ``/serve/traces``."""
        from deeplearning4j_tpu.observability.tracing import \
            global_trace_store

        return {"traces": global_trace_store().list()}

    def serve_trace(self, trace_id: str):
        """One full span tree by id, or None."""
        from deeplearning4j_tpu.observability.tracing import \
            global_trace_store

        return global_trace_store().get(trace_id)

    def serve_slo(self) -> dict:
        """Current SLO burn-rate evaluation for ``/serve/slo`` (runs the
        engine attached to a live InferenceServer when one exists; a
        standalone UI evaluates a fresh engine over the same process-global
        histograms, so the pane works either way)."""
        from deeplearning4j_tpu.keras_server.serving import serve_slo

        return serve_slo()

    def telemetry_data(self) -> dict:
        """JSON registry snapshot + recent compile events for
        ``/train/telemetry/data`` (same data as /metrics plus the compile
        event log, which has no Prometheus shape)."""
        from deeplearning4j_tpu.observability import (global_registry,
                                                      global_tracker)

        return {"metrics": global_registry().snapshot(),
                "compile_events": global_tracker().snapshot_events(),
                "step": global_tracker().step}

    def health_data(self) -> dict:
        """Training-health snapshot for ``/train/health``: the dl4j_health_*
        / watchdog / MFU gauge families plus flight-recorder state, so one
        request answers "is this run diverging, stalled, or dumping"."""
        from deeplearning4j_tpu.observability import (global_recorder,
                                                      global_registry)
        from deeplearning4j_tpu.observability.watchdog import global_watchdog

        prefixes = ("dl4j_health_", "dl4j_watchdog_", "dl4j_flight_",
                    "dl4j_step_mfu")
        metrics = {name: fam
                   for name, fam in global_registry().snapshot().items()
                   if name.startswith(prefixes)}
        rec = global_recorder()
        wd = global_watchdog()
        return {
            "metrics": metrics,
            "recorder": {"enabled": rec.enabled, "events": len(rec),
                         "dropped": rec.dropped, "capacity": rec.capacity},
            "watchdog": None if wd is None else {
                "threshold_s": wd.threshold_s, "stalls": wd.stalls},
        }

    def health_bundles(self) -> dict:
        """Flight-recorder bundle manifests (newest first) for
        ``/train/health/bundles``."""
        from deeplearning4j_tpu.observability import global_recorder

        return {"bundles": global_recorder().list_bundles()}

    def profiles(self) -> dict:
        """Trace-capture index (newest first) for ``/train/profiles`` —
        the sqlite-backed index survives process death, so this also lists
        captures from earlier runs under the same profile dir."""
        from deeplearning4j_tpu.observability.profiler import \
            global_trace_session

        session = global_trace_session()
        return {"base_dir": session.base_dir, "active": session.active,
                "profiles": session.index_entries()}

    def profile_summary(self, trace: Optional[str]) -> dict:
        """Attribution JSON of one indexed capture for
        ``/train/profiles/summary?trace=<logdir>``. The requested value must
        equal an index entry's logdir verbatim; the summary path comes from
        the index, never from the request."""
        import os

        from deeplearning4j_tpu.observability.profiler import (
            ATTRIBUTION_FILE, global_trace_session)

        if not trace:
            return {"error": "missing ?trace=<logdir>"}
        for entry in global_trace_session().index_entries():
            if entry.get("logdir") != trace:
                continue
            path = entry.get("summary_path") \
                or os.path.join(trace, ATTRIBUTION_FILE)
            try:
                with open(path) as f:
                    return json.load(f)
            except (OSError, ValueError) as e:
                return {"error": f"unreadable attribution summary: {e!r}",
                        "entry": entry}
        return {"error": "trace not in the profile index"}

    def histogram_data(self, session: Optional[str] = None) -> dict:
        """Latest histograms per variable (reference HistogramModule)."""
        reports = self._all_reports(session)
        if not reports:
            return {"params": {}, "gradients": {}, "updates": {}}
        r = reports[-1]
        def fmt(section):
            return {name: {"meanMagnitude": mm, "bins": hist,
                           "min": lo, "max": hi}
                    for name, (mm, hist, (lo, hi)) in section.items()}
        return {"iteration": r.iteration,
                "params": fmt(r.param_stats),
                "gradients": fmt(r.gradient_stats),
                "updates": fmt(r.update_stats)}

    def histograms_page(self, session: Optional[str], lang: Optional[str]) -> str:
        """Server-side rendered histogram page: ChartHistogram components per
        recorded variable, grouped params/gradients/updates (reference
        HistogramModule's rendered view over ui-components charts)."""
        from deeplearning4j_tpu.ui.components import (
            ChartHistogram, ComponentText, render_page)
        from deeplearning4j_tpu.ui.i18n import I18N

        i18n = I18N.get_instance()
        msg = lambda k: i18n.get_message(k, lang)
        data = self.histogram_data(session)
        comps = []
        for section, key in (("params", "train.histograms.params"),
                             ("gradients", "train.histograms.gradients"),
                             ("updates", "train.histograms.updates")):
            entries = data.get(section) or {}
            if not entries:
                continue
            comps.append(ComponentText(msg(key), heading=True))
            for name, v in sorted(entries.items()):
                bins = v["bins"]
                if not bins:
                    continue
                lo, hi = v["min"], v["max"]
                width = (hi - lo) / len(bins) if hi > lo else 1.0
                lowers = [lo + i * width for i in range(len(bins))]
                uppers = [lo + (i + 1) * width for i in range(len(bins))]
                comps.append(ChartHistogram(name, lowers, uppers, bins))
        if not comps:
            comps = [ComponentText(msg("train.histograms.none"))]
        title = f"{msg('train.pagetitle')} - {msg('train.nav.histograms')}"
        nav = _localize(_NAV, lang)
        page = render_page(title, *comps)
        return page.replace("<body>", "<body>" + nav, 1)

    def set_tsne(self, payload: dict) -> None:
        """TsneModule upload target (coords + optional labels)."""
        self._tsne = {"coords": payload.get("coords", []),
                      "labels": payload.get("labels", [])}

    def tsne_data(self) -> dict:
        return self._tsne

    # ----------------------------------------------------------- model graph
    def attach_model(self, net) -> None:
        """Register a model so the rendered Model page can draw its network
        graph (FlowModule equivalent, reference FlowIterationListener)."""
        self._model_graph = describe_model(net)

    def model_graph(self) -> dict:
        return getattr(self, "_model_graph", {"nodes": [], "edges": []})

    # ------------------------------------------------- convolutional module
    def set_conv_data(self, payload: dict) -> None:
        """ConvolutionalListenerModule upload target: per-layer activation
        maps as nested lists (reference ConvolutionalIterationListener)."""
        self._conv = {"iteration": int(payload.get("iteration", 0)),
                      "maps": payload.get("maps", [])}

    def conv_data(self) -> dict:
        return getattr(self, "_conv", {"iteration": 0, "maps": []})


def describe_model(net) -> dict:
    """Architecture graph for the Model page / Flow module: nodes with type
    and parameter counts, edges in forward order. Works for both network
    types (reference FlowIterationListener builds the same ModelInfo)."""
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils.pytree import num_params

    nodes = [{"name": "input", "type": "input", "nParams": 0}]
    edges = []
    if isinstance(net, MultiLayerNetwork):
        prev = "input"
        for i, layer in enumerate(net.conf.layers):
            name = f"layer_{i}"
            n = num_params(net.params_list[i]) if net.params_list else 0
            nodes.append({"name": name, "type": type(layer).__name__,
                          "nParams": int(n)})
            edges.append([prev, name])
            prev = name
        return {"nodes": nodes, "edges": edges}
    if isinstance(net, ComputationGraph):
        nodes = [{"name": n, "type": "input", "nParams": 0}
                 for n in net.conf.network_inputs]
        order = net.conf.topological_order or net.conf.topo_sort()
        for name in order:
            vertex = net.conf.vertices[name]
            layer = getattr(vertex, "layer", None)
            vtype = (type(layer).__name__ if layer is not None
                     else type(vertex).__name__)
            n = (num_params(net.params_list.get(name, {}))
                 if net.params_list else 0)
            nodes.append({"name": name, "type": vtype, "nParams": int(n)})
            for src in net.conf.vertex_inputs[name]:
                edges.append([src, name])
        return {"nodes": nodes, "edges": edges}
    raise TypeError(f"cannot describe model of type {type(net)}")


class ConvolutionalIterationListener:
    """Posts conv-layer activation maps to the UI every N iterations
    (reference ConvolutionalIterationListener.java renders activation
    probability images into the ConvolutionalListenerModule). TPU-native:
    activations are computed with one extra jitted forward on a held-out
    probe batch, downsampled to ``max_channels`` maps of the FIRST probe
    example, and stored as JSON-ready nested lists."""

    def __init__(self, ui: "UIServer", probe_x, frequency: int = 10,
                 max_channels: int = 8):
        import numpy as np
        self.ui = ui
        self.probe_x = np.asarray(probe_x)
        self.frequency = max(1, frequency)
        self.max_channels = max_channels

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        import numpy as np
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer

        acts = model.feed_forward(self.probe_x)
        maps = []
        for i, layer in enumerate(model.conf.layers):
            if not isinstance(layer, ConvolutionLayer):
                continue
            a = np.asarray(acts[i])  # NHWC
            if a.ndim != 4:
                continue
            chans = [a[0, :, :, c].tolist()
                     for c in range(min(a.shape[-1], self.max_channels))]
            maps.append({"layer": f"layer_{i}", "channels": chans})
        if maps:
            self.ui.set_conv_data({"iteration": iteration, "maps": maps})


class RemoteUIStatsStorageRouter:
    """HTTP client posting stats to a remote UIServer
    (reference core api/storage/impl/RemoteUIStatsStorageRouter.java)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def put_update(self, record) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url + "/remoteReceive", data=record.encode(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            if resp.status != 200:
                raise IOError(f"Remote post failed: {resp.status}")

    def put_static_info(self, record) -> None:
        self.put_update(record)
