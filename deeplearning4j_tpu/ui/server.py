"""Training UI web server.

Reference: deeplearning4j-play ui/play/PlayUIServer.java — a web server with
pluggable modules (TrainModule overview/model/system pages,
RemoteReceiverModule POST endpoint) attached to StatsStorage instances via
listeners. Here: stdlib http.server in a daemon thread serving JSON endpoints
plus one self-contained HTML page (inline canvas charts, no external assets —
the environment has zero egress), and the remote-receiver POST route.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>DL4J-TPU Training UI</title>
<style>
body { font-family: sans-serif; margin: 20px; background: #fafafa; }
h2 { color: #333; } .chart { background: #fff; border: 1px solid #ddd;
margin-bottom: 16px; padding: 8px; }
</style></head>
<body>
<h2>Training overview</h2>
<div class="chart"><canvas id="score" width="900" height="260"></canvas></div>
<div class="chart"><canvas id="ratio" width="900" height="260"></canvas></div>
<script>
function drawSeries(canvasId, xs, ys, label, color) {
  const c = document.getElementById(canvasId), ctx = c.getContext('2d');
  ctx.clearRect(0, 0, c.width, c.height);
  if (!ys.length) return;
  const ymin = Math.min(...ys), ymax = Math.max(...ys), pad = 36;
  const sx = (c.width - 2*pad) / Math.max(xs.length - 1, 1);
  const sy = (c.height - 2*pad) / Math.max(ymax - ymin, 1e-9);
  ctx.strokeStyle = '#999'; ctx.strokeRect(pad, pad, c.width-2*pad, c.height-2*pad);
  ctx.fillStyle = '#333'; ctx.fillText(label + ' (last: ' +
      ys[ys.length-1].toPrecision(5) + ')', pad, pad - 6);
  ctx.strokeStyle = color; ctx.beginPath();
  ys.forEach((y, i) => { const px = pad + i*sx,
      py = c.height - pad - (y - ymin)*sy;
      i ? ctx.lineTo(px, py) : ctx.moveTo(px, py); });
  ctx.stroke();
}
async function refresh() {
  const r = await fetch('/train/overview/data'); const d = await r.json();
  drawSeries('score', d.iterations, d.scores, 'Model score vs iteration', '#c33');
  drawSeries('ratio', d.iterations, d.updateRatios,
             'Mean update:parameter ratio (log10)', '#36c');
}
refresh(); setInterval(refresh, 2000);
</script>
</body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTPUUIServer/1.0"
    ui: "UIServer" = None

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path = urlparse(self.path).path
        if path in ("/", "/train", "/train/overview"):
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/train/overview/data":
            self._json(self.ui.overview_data())
        elif path == "/train/sessions":
            self._json(self.ui.sessions())
        elif path == "/train/model/data":
            q = parse_qs(urlparse(self.path).query)
            self._json(self.ui.model_data(q.get("session", [None])[0]))
        elif path == "/train/system/data":
            self._json(self.ui.system_data())
        elif path == "/train/histograms/data":
            # HistogramModule equivalent: latest param/gradient/update
            # histograms per variable
            q = parse_qs(urlparse(self.path).query)
            self._json(self.ui.histogram_data(q.get("session", [None])[0]))
        elif path == "/tsne/data":
            # TsneModule equivalent: last uploaded embedding coords
            self._json(self.ui.tsne_data())
        else:
            self._json({"error": "not found"}, 404)

    def do_POST(self):
        path = urlparse(self.path).path
        if path == "/tsne/upload":
            # TsneModule upload: JSON {"coords": [[x, y], ...], "labels": []}
            length = int(self.headers.get("Content-Length", "0"))
            try:
                payload = json.loads(self.rfile.read(length))
                self.ui.set_tsne(payload)
            except Exception as e:
                self._json({"status": "error", "detail": str(e)}, 400)
                return
            self._json({"status": "ok"})
        elif path == "/remoteReceive":
            # RemoteReceiverModule equivalent: accept encoded StatsReports
            length = int(self.headers.get("Content-Length", "0"))
            data = self.rfile.read(length)
            try:
                report = StatsReport.decode(data)
            except Exception as e:
                self._json({"status": "error", "detail": str(e)}, 400)
                return
            self.ui.post_remote(report)
            self._json({"status": "ok"})
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """reference UIServer.getInstance() + attach(statsStorage)"""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self.port = port
        self._storages: List[StatsStorage] = []
        self._remote_storage: Optional[StatsStorage] = None
        self._tsne: dict = {"coords": [], "labels": []}
        handler = type("BoundHandler", (_Handler,), {"ui": self})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        self._storages.remove(storage)

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None) -> None:
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

        self._remote_storage = storage or InMemoryStatsStorage()
        self.attach(self._remote_storage)

    def post_remote(self, report: StatsReport) -> None:
        if self._remote_storage is None:
            self.enable_remote_listener()
        self._remote_storage.put_update(report)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None

    # ------------------------------------------------------------------ data API
    def _all_reports(self, session: Optional[str] = None) -> List[StatsReport]:
        out: List[StatsReport] = []
        for storage in self._storages:
            for sid in storage.list_session_ids():
                if session and sid != session:
                    continue
                for wid in storage.list_worker_ids_for_session(sid):
                    for blob in storage.get_all_updates_after(
                            sid, StatsReport.TYPE_ID, wid, -1):
                        try:
                            out.append(StatsReport.decode(blob))
                        except ValueError:
                            pass
        out.sort(key=lambda r: (r.timestamp, r.iteration))
        return out

    def sessions(self) -> List[str]:
        out: List[str] = []
        for storage in self._storages:
            out.extend(storage.list_session_ids())
        return sorted(set(out))

    def overview_data(self) -> dict:
        reports = self._all_reports()
        import math

        ratios = []
        for r in reports:
            pairs = [(r.update_stats[k][0], r.param_stats[k][0])
                     for k in r.update_stats if k in r.param_stats]
            vals = [u / p for u, p in pairs if p > 0 and u > 0]
            ratios.append(math.log10(sum(vals) / len(vals)) if vals else -10.0)
        return {
            "iterations": [r.iteration for r in reports],
            "scores": [r.score for r in reports],
            "updateRatios": ratios,
            "iterationTimesMs": [r.iteration_time_ms for r in reports],
        }

    def model_data(self, session: Optional[str] = None) -> dict:
        reports = self._all_reports(session)
        if not reports:
            return {"layers": {}}
        last = reports[-1]
        return {
            "layers": {
                name: {"meanMagnitude": mm, "histogram": hist,
                       "range": list(rng)}
                for name, (mm, hist, rng) in last.param_stats.items()
            },
            "gradients": {
                name: {"meanMagnitude": mm}
                for name, (mm, _, _) in last.gradient_stats.items()
            },
        }

    def system_data(self) -> dict:
        reports = self._all_reports()
        return {
            "memRssBytes": [r.mem_rss_bytes for r in reports],
            "deviceMemBytes": [r.device_mem_bytes for r in reports],
            "timestamps": [r.timestamp for r in reports],
        }

    def histogram_data(self, session: Optional[str] = None) -> dict:
        """Latest histograms per variable (reference HistogramModule)."""
        reports = self._all_reports(session)
        if not reports:
            return {"params": {}, "gradients": {}, "updates": {}}
        r = reports[-1]
        def fmt(section):
            return {name: {"meanMagnitude": mm, "bins": hist,
                           "min": lo, "max": hi}
                    for name, (mm, hist, (lo, hi)) in section.items()}
        return {"iteration": r.iteration,
                "params": fmt(r.param_stats),
                "gradients": fmt(r.gradient_stats),
                "updates": fmt(r.update_stats)}

    def set_tsne(self, payload: dict) -> None:
        """TsneModule upload target (coords + optional labels)."""
        self._tsne = {"coords": payload.get("coords", []),
                      "labels": payload.get("labels", [])}

    def tsne_data(self) -> dict:
        return self._tsne


class RemoteUIStatsStorageRouter:
    """HTTP client posting stats to a remote UIServer
    (reference core api/storage/impl/RemoteUIStatsStorageRouter.java)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def put_update(self, record) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url + "/remoteReceive", data=record.encode(),
            headers={"Content-Type": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            if resp.status != 200:
                raise IOError(f"Remote post failed: {resp.status}")

    def put_static_info(self, record) -> None:
        self.put_update(record)
