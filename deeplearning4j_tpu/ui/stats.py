"""StatsListener: per-iteration training statistics.

Reference: ui-model ui/stats/BaseStatsListener.java:43 — iterationDone:273
collects score, timings, JVM/off-heap memory:324, GC counts:356, and
param/gradient/update histograms + mean magnitudes:508, encoded with SBE
(ui/stats/sbe/*). Here the wire format is a compact struct-packed binary codec
(flat little-endian records in place of generated SBE codecs) and memory stats
come from the Python runtime + jax device stats.
"""
from __future__ import annotations

import json
import resource
import struct
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.ui.storage import Persistable, StatsStorageRouter

_MAGIC = b"DLTS"
_VERSION = 1


class StatsReport(Persistable):
    """One iteration's stats record (reference SbeStatsReport)."""

    TYPE_ID = "StatsListener"

    def __init__(self, session_id: str = "", worker_id: str = "main",
                 timestamp: int = 0):
        self.session_id = session_id
        self.worker_id = worker_id
        self.timestamp = timestamp
        self.iteration = 0
        self.score = 0.0
        self.iteration_time_ms = 0.0
        self.samples_per_sec = 0.0
        self.mem_rss_bytes = 0
        self.device_mem_bytes = 0
        # name -> (mean_magnitude, histogram counts, (min, max))
        self.param_stats: Dict[str, Tuple[float, List[int], Tuple[float, float]]] = {}
        self.gradient_stats: Dict[str, Tuple[float, List[int], Tuple[float, float]]] = {}
        self.update_stats: Dict[str, Tuple[float, List[int], Tuple[float, float]]] = {}

    # ------------------------------------------------------------------ Persistable
    def get_session_id(self) -> str:
        return self.session_id

    def get_type_id(self) -> str:
        return self.TYPE_ID

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_timestamp(self) -> int:
        return self.timestamp

    def encode(self) -> bytes:
        """Compact binary: fixed header + JSON-free packed stats sections.
        Uses the native C++ codec (nativert, SBE-codec equivalent) when the
        runtime library is available; the pure-Python encoder below emits the
        identical DLTS wire format."""
        from deeplearning4j_tpu import nativert
        native = nativert.encode_stats_native(
            self.session_id, self.worker_id, self.timestamp, self.iteration,
            self.score, self.iteration_time_ms, self.samples_per_sec,
            self.mem_rss_bytes, self.device_mem_bytes,
            [self.param_stats, self.gradient_stats, self.update_stats])
        if native is not None:
            return native
        out = bytearray()
        out += _MAGIC
        out += struct.pack("<H", _VERSION)
        sid = self.session_id.encode()
        wid = self.worker_id.encode()
        out += struct.pack("<H", len(sid)) + sid
        out += struct.pack("<H", len(wid)) + wid
        out += struct.pack("<qid dd qq", self.timestamp, self.iteration,
                           self.score, self.iteration_time_ms,
                           self.samples_per_sec, self.mem_rss_bytes,
                           self.device_mem_bytes)
        for section in (self.param_stats, self.gradient_stats, self.update_stats):
            out += struct.pack("<H", len(section))
            for name, (mm, hist, (lo, hi)) in section.items():
                nb = name.encode()
                out += struct.pack("<H", len(nb)) + nb
                out += struct.pack("<ddd", mm, lo, hi)
                out += struct.pack("<H", len(hist))
                out += struct.pack(f"<{len(hist)}i", *hist)
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "StatsReport":
        if data[:4] != _MAGIC:
            raise ValueError("Not a StatsReport record")
        off = 6
        def take(fmt):
            nonlocal off
            size = struct.calcsize(fmt)
            vals = struct.unpack_from(fmt, data, off)
            off += size
            return vals
        (slen,) = take("<H")
        sid = data[off:off + slen].decode(); off += slen
        (wlen,) = take("<H")
        wid = data[off:off + wlen].decode(); off += wlen
        r = cls(sid, wid)
        (r.timestamp, r.iteration, r.score, r.iteration_time_ms,
         r.samples_per_sec, r.mem_rss_bytes, r.device_mem_bytes) = take("<qid dd qq")
        for section in (r.param_stats, r.gradient_stats, r.update_stats):
            (n,) = take("<H")
            for _ in range(n):
                (nlen,) = take("<H")
                name = data[off:off + nlen].decode(); off += nlen
                mm, lo, hi = take("<ddd")
                (hlen,) = take("<H")
                hist = list(take(f"<{hlen}i"))
                section[name] = (mm, hist, (lo, hi))
        return r

    def to_json(self) -> str:
        return json.dumps({
            "sessionID": self.session_id, "workerID": self.worker_id,
            "timestamp": self.timestamp, "iteration": self.iteration,
            "score": self.score, "iterationTimeMs": self.iteration_time_ms,
            "samplesPerSec": self.samples_per_sec,
            "memRssBytes": self.mem_rss_bytes,
            "deviceMemBytes": self.device_mem_bytes,
            "paramMeanMagnitudes": {k: v[0] for k, v in self.param_stats.items()},
            "gradientMeanMagnitudes": {k: v[0] for k, v in self.gradient_stats.items()},
            "updateMeanMagnitudes": {k: v[0] for k, v in self.update_stats.items()},
        })


def _array_stats(arr: np.ndarray, bins: int) -> Tuple[float, List[int], Tuple[float, float]]:
    flat = np.ravel(np.asarray(arr, np.float64))
    if flat.size == 0:
        return 0.0, [0] * bins, (0.0, 0.0)
    mm = float(np.mean(np.abs(flat)))
    lo, hi = float(flat.min()), float(flat.max())
    hist, _ = np.histogram(flat, bins=bins,
                           range=(lo, hi if hi > lo else lo + 1e-12))
    return mm, hist.astype(int).tolist(), (lo, hi)


class StatsListener:
    """Collects stats per iteration and routes them to storage
    (reference BaseStatsListener.iterationDone:273)."""

    def __init__(self, router: StatsStorageRouter, session_id: Optional[str] = None,
                 worker_id: str = "main", frequency: int = 1,
                 collect_histograms: bool = True, histogram_bins: int = 20):
        self.router = router
        self.session_id = session_id or f"session_{int(time.time()*1000)}"
        self.worker_id = worker_id
        self.frequency = max(1, frequency)
        self.collect_histograms = collect_histograms
        self.histogram_bins = histogram_bins
        self._last_time: Optional[float] = None
        self._last_params: Optional[dict] = None

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        now = time.time()
        r = StatsReport(self.session_id, self.worker_id, int(now * 1000))
        r.iteration = iteration
        r.score = float(model.score_value)
        if self._last_time is not None:
            r.iteration_time_ms = (now - self._last_time) * 1000 / self.frequency
        self._last_time = now
        r.mem_rss_bytes = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024

        params = getattr(model, "params_list", None)
        if self.collect_histograms and params is not None:
            flat = _flatten_named(params)
            for name, arr in flat.items():
                r.param_stats[name] = _array_stats(arr, self.histogram_bins)
            if self._last_params is not None:
                for name, arr in flat.items():
                    prev = self._last_params.get(name)
                    if prev is not None and prev.shape == np.shape(arr):
                        r.update_stats[name] = _array_stats(
                            np.asarray(arr) - prev, self.histogram_bins)
            self._last_params = {k: np.asarray(v).copy() for k, v in flat.items()}
        self.router.put_update(r)

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass


def _flatten_named(params, prefix="") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(params, dict):
        items = params.items()
    elif isinstance(params, (list, tuple)):
        items = enumerate(params)
    else:
        return {prefix or "param": np.asarray(params)}
    for k, v in items:
        name = f"{prefix}{k}"
        if isinstance(v, (dict, list, tuple)):
            out.update(_flatten_named(v, name + "_"))
        elif v is not None and hasattr(v, "shape"):
            out[name] = np.asarray(v)
    return out
