"""Graph structure for vertex-embedding models.

Reference: deeplearning4j-graph — graph/api/{Vertex,Edge,IGraph}.java and the
adjacency-list graph/impl/Graph.java; loaders in data/impl/ (edge-list and
adjacency-list file formats).
"""
from __future__ import annotations

import dataclasses
from typing import Generic, List, Optional, TypeVar

V = TypeVar("V")

@dataclasses.dataclass
class Vertex(Generic[V]):
    idx: int
    value: Optional[V] = None


@dataclasses.dataclass
class Edge:
    from_idx: int
    to_idx: int
    weight: float = 1.0
    directed: bool = False


class IGraph:
    def num_vertices(self) -> int:
        raise NotImplementedError

    def get_vertex(self, idx: int) -> Vertex:
        raise NotImplementedError

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        raise NotImplementedError

    def get_edges_out(self, idx: int) -> List[Edge]:
        raise NotImplementedError

    def get_vertex_degree(self, idx: int) -> int:
        return len(self.get_connected_vertex_indices(idx))


class Graph(IGraph):
    """Adjacency-list graph (reference graph/impl/Graph.java)."""

    def __init__(self, num_vertices: int, allow_multiple_edges: bool = True):
        self._vertices = [Vertex(i) for i in range(num_vertices)]
        self._adj: List[List[Edge]] = [[] for _ in range(num_vertices)]
        self.allow_multiple_edges = allow_multiple_edges

    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def set_vertex_value(self, idx: int, value) -> None:
        self._vertices[idx].value = value

    def add_edge(self, from_idx: int, to_idx: int, weight: float = 1.0,
                 directed: bool = False) -> None:
        e = Edge(from_idx, to_idx, weight, directed)
        if not self.allow_multiple_edges and any(
                x.to_idx == to_idx for x in self._adj[from_idx]):
            return
        self._adj[from_idx].append(e)
        if not directed:
            self._adj[to_idx].append(Edge(to_idx, from_idx, weight, directed))

    def get_edges_out(self, idx: int) -> List[Edge]:
        return list(self._adj[idx])

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        return [e.to_idx for e in self._adj[idx]]

    # ------------------------------------------------------------------ loaders
    @staticmethod
    def load_edge_list(path: str, num_vertices: int, directed: bool = False,
                       delimiter: Optional[str] = None,
                       weighted: bool = False) -> "Graph":
        """Edge-list file: 'from to [weight]' per line
        (reference data/impl/EdgeLineProcessor / GraphLoader.loadUndirectedGraphEdgeListFile)."""
        g = Graph(num_vertices)
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                w = float(parts[2]) if weighted and len(parts) > 2 else 1.0
                g.add_edge(int(parts[0]), int(parts[1]), w, directed)
        return g

    @staticmethod
    def load_adjacency_list(path: str, delimiter: Optional[str] = None) -> "Graph":
        """Adjacency-list file: 'vertex n1 n2 n3...' per line (directed edges)."""
        rows = []
        max_v = -1
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                idxs = [int(x) for x in line.split(delimiter)]
                rows.append(idxs)
                max_v = max(max_v, *idxs)
        g = Graph(max_v + 1)
        for row in rows:
            for to in row[1:]:
                g.add_edge(row[0], to, 1.0, directed=True)
        return g
