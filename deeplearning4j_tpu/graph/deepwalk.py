"""DeepWalk vertex embeddings.

Reference: graph/models/deepwalk/DeepWalk.java:31 — fit(IGraph, walkLength):93
generates random-walk sequences and trains skip-gram with hierarchical softmax
(InMemoryGraphLookupTable + GraphHuffman). Here the walks feed the shared
SequenceVectors engine (vertex indices as tokens), reusing the jitted
skip-gram/HS kernel — the reference's dedicated graph lookup table collapses
into the common one.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import IGraph
from deeplearning4j_tpu.graph.walkers import (
    RandomWalkIterator, WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.nlp.sequencevectors import SequenceVectors


class DeepWalk:
    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, epochs: int = 1,
                 batch_size: int = 512, seed: int = 123,
                 weighted_walks: bool = False):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.seed = seed
        self.weighted_walks = weighted_walks
        self.model: Optional[SequenceVectors] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def vector_size(self, n: int):
            self._kw["vector_size"] = n
            return self

        def window_size(self, n: int):
            self._kw["window_size"] = n
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def epochs(self, n: int):
            self._kw["epochs"] = n
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def weighted(self, flag: bool):
            self._kw["weighted_walks"] = flag
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    @staticmethod
    def builder() -> "DeepWalk.Builder":
        return DeepWalk.Builder()

    # ------------------------------------------------------------------ training
    def fit(self, graph: IGraph, walk_length: int = 40,
            walks_per_vertex: int = 1) -> None:
        walker_cls = (WeightedRandomWalkIterator if self.weighted_walks
                      else RandomWalkIterator)
        sequences: List[List[str]] = []
        for rep in range(walks_per_vertex):
            walker = walker_cls(graph, walk_length, seed=self.seed + rep)
            sequences.extend([str(v) for v in walk] for walk in walker)
        self.model = SequenceVectors(
            vector_length=self.vector_size, window=self.window_size,
            learning_rate=self.learning_rate, epochs=self.epochs,
            use_hierarchic_softmax=True, negative=0,
            min_word_frequency=1, batch_size=self.batch_size, seed=self.seed)
        self.model.fit(sequences)

    # ------------------------------------------------------------------ access
    def get_vertex_vector(self, vertex_idx: int) -> np.ndarray:
        vec = self.model.get_word_vector(str(vertex_idx))
        if vec is None:
            raise KeyError(f"vertex {vertex_idx} not in model")
        return vec

    def similarity(self, v1: int, v2: int) -> float:
        return self.model.similarity(str(v1), str(v2))

    def vertices_nearest(self, vertex_idx: int, top_n: int = 10) -> List[int]:
        return [int(w) for w in self.model.words_nearest(str(vertex_idx), top_n)]
