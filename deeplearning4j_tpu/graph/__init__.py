from deeplearning4j_tpu.graph.deepwalk import DeepWalk
from deeplearning4j_tpu.graph.graph import Edge, Graph, Vertex
from deeplearning4j_tpu.graph.walkers import (
    RandomWalkIterator, WeightedRandomWalkIterator,
)

__all__ = ["Graph", "Vertex", "Edge", "RandomWalkIterator",
           "WeightedRandomWalkIterator", "DeepWalk"]
