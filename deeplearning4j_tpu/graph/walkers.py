"""Random-walk sequence generators.

Reference: deeplearning4j-graph iterator/RandomWalkIterator.java +
WeightedRandomWalkIterator.java, with NoEdgeHandling SELF_LOOP_ON_DISCONNECTED /
EXCEPTION_ON_DISCONNECTED semantics.
"""
from __future__ import annotations

from typing import Iterator, List

import numpy as np

from deeplearning4j_tpu.graph.graph import IGraph

SELF_LOOP_ON_DISCONNECTED = "self_loop"
EXCEPTION_ON_DISCONNECTED = "exception"


class RandomWalkIterator:
    """Uniform random walks of fixed length, one starting at each vertex
    (shuffled start order, as the reference's GraphWalkIteratorProvider does)."""

    def __init__(self, graph: IGraph, walk_length: int, seed: int = 123,
                 no_edge_handling: str = SELF_LOOP_ON_DISCONNECTED):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self._rng = np.random.default_rng(seed)

    def _next_vertex(self, current: int) -> int:
        neighbors = self.graph.get_connected_vertex_indices(current)
        if not neighbors:
            if self.no_edge_handling == EXCEPTION_ON_DISCONNECTED:
                raise ValueError(f"Vertex {current} has no edges")
            return current  # self loop
        return int(neighbors[self._rng.integers(0, len(neighbors))])

    def walk_from(self, start: int) -> List[int]:
        walk = [start]
        current = start
        for _ in range(self.walk_length):
            current = self._next_vertex(current)
            walk.append(current)
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        order = self._rng.permutation(self.graph.num_vertices())
        for start in order:
            yield self.walk_from(int(start))

    def reset(self) -> None:
        self._rng = np.random.default_rng(self.seed)


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Transition probability proportional to edge weight
    (reference WeightedRandomWalkIterator.java)."""

    def _next_vertex(self, current: int) -> int:
        edges = self.graph.get_edges_out(current)
        if not edges:
            if self.no_edge_handling == EXCEPTION_ON_DISCONNECTED:
                raise ValueError(f"Vertex {current} has no edges")
            return current
        weights = np.array([e.weight for e in edges], np.float64)
        probs = weights / weights.sum()
        return int(edges[self._rng.choice(len(edges), p=probs)].to_idx)
