"""KMeans clustering.

Reference: deeplearning4j-core clustering/kmeans/KMeansClustering.java (+
clustering/algorithm/BaseClusteringAlgorithm: iterationsation strategy with max
iterations / distance-variation convergence).

TPU-native: kmeans++ seeding on host, then Lloyd iterations as ONE jitted
``lax.while_loop`` — assignment (pairwise distances on the MXU) and centroid
update (segment mean) both stay on device.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class ClusterSet(NamedTuple):
    centers: jax.Array        # (k, d)
    assignments: jax.Array    # (n,)
    iterations: jax.Array
    inertia: jax.Array


def _plus_plus_init(x: np.ndarray, k: int, rng: np.random.Generator) -> np.ndarray:
    n = x.shape[0]
    centers = [x[rng.integers(0, n)]]
    for _ in range(1, k):
        d2 = np.min(
            ((x[:, None, :] - np.stack(centers)[None]) ** 2).sum(-1), axis=1)
        probs = d2 / max(d2.sum(), 1e-12)
        centers.append(x[rng.choice(n, p=probs)])
    return np.stack(centers)


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-4,
                 seed: int = 0, distance: str = "euclidean"):
        if distance not in ("euclidean", "cosine", "manhattan"):
            raise ValueError(f"Unknown distance: {distance}")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.distance = distance

    @staticmethod
    def setup(k: int, max_iterations: int = 100, distance: str = "euclidean",
              seed: int = 0) -> "KMeansClustering":
        """reference KMeansClustering.setup(clusterCount, maxIterations, distanceFunction)"""
        return KMeansClustering(k, max_iterations, distance=distance, seed=seed)

    def _distances(self, x, centers):
        if self.distance == "euclidean":
            return ((x[:, None, :] - centers[None]) ** 2).sum(-1)
        if self.distance == "manhattan":
            return jnp.abs(x[:, None, :] - centers[None]).sum(-1)
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        cn = centers / jnp.maximum(jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)
        return 1.0 - xn @ cn.T

    def apply_to(self, points) -> ClusterSet:
        x_np = np.asarray(points, np.float32)
        init = _plus_plus_init(x_np, self.k, np.random.default_rng(self.seed))
        x = jnp.asarray(x_np)
        k, tol, max_it = self.k, self.tol, self.max_iterations

        def assign(centers):
            return jnp.argmin(self._distances(x, centers), axis=1)

        def update(assignments):
            onehot = jax.nn.one_hot(assignments, k, dtype=x.dtype)  # (n, k)
            sums = onehot.T @ x                                     # (k, d)
            counts = onehot.sum(0)[:, None]
            return jnp.where(counts > 0, sums / jnp.maximum(counts, 1), 0.0)

        def cond(st):
            centers, prev, it, moved = st
            return jnp.logical_and(it < max_it, moved > tol)

        def body(st):
            centers, _, it, _ = st
            a = assign(centers)
            new_centers = update(a)
            moved = jnp.max(jnp.abs(new_centers - centers))
            return new_centers, a, it + 1, moved

        @jax.jit
        def run(centers0):
            a0 = assign(centers0)
            centers, a, it, _ = jax.lax.while_loop(
                cond, body, (centers0, a0, jnp.int32(0), jnp.float32(jnp.inf)))
            a = assign(centers)
            d = self._distances(x, centers)
            inertia = jnp.sum(jnp.min(d, axis=1))
            return ClusterSet(centers, a, it, inertia)

        return run(jnp.asarray(init))

    def predict(self, cluster_set: ClusterSet, points) -> np.ndarray:
        x = jnp.asarray(np.asarray(points, np.float32))
        return np.asarray(jnp.argmin(self._distances(x, cluster_set.centers), axis=1))
