"""KD-tree for exact nearest-neighbor search.

Reference: deeplearning4j-core clustering/kdtree/KDTree.java (insert/nn/knn over
HyperRect). Host-side structure (tree search is pointer-chasing, not MXU work);
median-split construction.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("idx", "dim", "left", "right")

    def __init__(self, idx: int, dim: int):
        self.idx = idx
        self.dim = dim
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, np.float64)
        n, self.d = self.points.shape
        self.root = self._build(list(range(n)), 0)

    def _build(self, idxs: List[int], depth: int) -> Optional[_Node]:
        if not idxs:
            return None
        dim = depth % self.d
        idxs.sort(key=lambda i: self.points[i, dim])
        mid = len(idxs) // 2
        node = _Node(idxs[mid], dim)
        node.left = self._build(idxs[:mid], depth + 1)
        node.right = self._build(idxs[mid + 1:], depth + 1)
        return node

    def nn(self, query) -> Tuple[int, float]:
        """Nearest neighbor: (index, distance)."""
        idx, dist = self.knn(query, 1)[0]
        return idx, dist

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance

        def visit(node: Optional[_Node]):
            if node is None:
                return
            p = self.points[node.idx]
            dist = float(np.linalg.norm(p - q))
            if len(heap) < k:
                heapq.heappush(heap, (-dist, node.idx))
            elif dist < -heap[0][0]:
                heapq.heapreplace(heap, (-dist, node.idx))
            diff = q[node.dim] - p[node.dim]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])

    def size(self) -> int:
        return self.points.shape[0]
