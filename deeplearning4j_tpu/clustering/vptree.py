"""Vantage-point tree for metric nearest-neighbor search.

Reference: deeplearning4j-core clustering/vptree/VPTree.java (used by
BarnesHutTsne for input-space neighbor finding). Median-distance splits,
priority-queue kNN search with tau pruning.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _VPNode:
    __slots__ = ("idx", "threshold", "inside", "outside")

    def __init__(self, idx: int):
        self.idx = idx
        self.threshold = 0.0
        self.inside: Optional["_VPNode"] = None
        self.outside: Optional["_VPNode"] = None


class VPTree:
    def __init__(self, points, distance: str = "euclidean", seed: int = 0):
        self.points = np.asarray(points, np.float64)
        self.distance = distance
        self._rng = np.random.default_rng(seed)
        self.root = self._build(list(range(self.points.shape[0])))

    def _dist(self, a: int, q) -> float:
        p = self.points[a]
        if self.distance == "cosine":
            num = float(p @ q)
            den = float(np.linalg.norm(p) * np.linalg.norm(q))
            return 1.0 - num / max(den, 1e-12)
        return float(np.linalg.norm(p - q))

    def _build(self, idxs: List[int]) -> Optional[_VPNode]:
        if not idxs:
            return None
        vp = idxs[self._rng.integers(0, len(idxs))]
        idxs = [i for i in idxs if i != vp]
        node = _VPNode(vp)
        if idxs:
            dists = [self._dist(i, self.points[vp]) for i in idxs]
            node.threshold = float(np.median(dists))
            inside = [i for i, dv in zip(idxs, dists) if dv < node.threshold]
            outside = [i for i, dv in zip(idxs, dists) if dv >= node.threshold]
            node.inside = self._build(inside)
            node.outside = self._build(outside)
        return node

    def knn(self, query, k: int) -> List[Tuple[int, float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap (negated)
        tau = [float("inf")]

        def visit(node: Optional[_VPNode]):
            if node is None:
                return
            d = self._dist(node.idx, q)
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.idx))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.idx))
                tau[0] = -heap[0][0]
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])
