"""Quad/SP-trees for Barnes-Hut approximation.

Reference: deeplearning4j-core clustering/quadtree/QuadTree.java (2-D) and
clustering/sptree/SpTree.java (n-D generalization with center-of-mass per cell,
used by BarnesHutTsne's repulsive-force approximation).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class SPTree:
    """n-dimensional space-partitioning tree storing center-of-mass per cell."""

    def __init__(self, data: np.ndarray, center: Optional[np.ndarray] = None,
                 width: Optional[np.ndarray] = None, indices: Optional[List[int]] = None,
                 leaf_capacity: int = 1, _depth: int = 0):
        self.data = data
        d = data.shape[1]
        if center is None:
            lo, hi = data.min(0), data.max(0)
            center = (lo + hi) / 2
            width = np.maximum((hi - lo) / 2 + 1e-5, 1e-5)
            indices = list(range(data.shape[0]))
        self.center = center
        self.width = width
        self.cum_size = len(indices)
        self.children: List[Optional[SPTree]] = []
        self.point_indices: List[int] = []
        if self.cum_size > 0:
            pts = data[indices]
            self.center_of_mass = pts.mean(0)
        else:
            self.center_of_mass = np.zeros(d)
        # subdivision: stop at capacity, identical points, or excessive depth
        if (self.cum_size <= leaf_capacity or _depth > 48
                or np.allclose(data[indices].std(0), 0)):
            self.point_indices = list(indices)
            return
        n_child = 2 ** d
        buckets: List[List[int]] = [[] for _ in range(n_child)]
        for i in indices:
            code = 0
            for dim in range(d):
                if data[i, dim] > center[dim]:
                    code |= 1 << dim
            buckets[code].append(i)
        for code in range(n_child):
            if not buckets[code]:
                self.children.append(None)
                continue
            offset = np.array([(1 if code >> dim & 1 else -1)
                               for dim in range(d)], np.float64)
            self.children.append(SPTree(
                data, center + offset * self.width / 2, self.width / 2,
                buckets[code], leaf_capacity, _depth + 1))

    def is_leaf(self) -> bool:
        return not self.children

    def compute_non_edge_forces(self, point_index: int, theta: float,
                                neg_f: np.ndarray) -> float:
        """Barnes-Hut negative-force accumulation for one point; returns the
        contribution to Z (sum of q_ij numerators). reference
        SpTree.computeNonEdgeForces."""
        if self.cum_size == 0:
            return 0.0
        if self.is_leaf() and self.point_indices == [point_index]:
            return 0.0
        diff = self.data[point_index] - self.center_of_mass
        dist2 = float(diff @ diff)
        max_width = float(self.width.max())
        if self.is_leaf() or max_width / np.sqrt(max(dist2, 1e-12)) < theta:
            # treat cell as single point at center of mass
            size = self.cum_size
            if (self.is_leaf() and point_index in self.point_indices):
                size -= 1
            if size <= 0:
                return 0.0
            q = 1.0 / (1.0 + dist2)
            mult = size * q
            neg_f += mult * q * diff
            return mult
        z = 0.0
        for child in self.children:
            if child is not None:
                z += child.compute_non_edge_forces(point_index, theta, neg_f)
        return z


class QuadTree(SPTree):
    """2-D specialization (reference clustering/quadtree/QuadTree.java)."""

    def __init__(self, data: np.ndarray, **kwargs):
        data = np.asarray(data, np.float64)
        if data.shape[1] != 2:
            raise ValueError("QuadTree requires 2-D data; use SPTree for n-D")
        super().__init__(data, **kwargs)
