"""t-SNE embedding.

Reference: deeplearning4j-core plot/Tsne.java (exact) + plot/BarnesHutTsne.java:64
(theta-approximated, VPTree input neighbors + SpTree repulsive forces).

TPU-native split: the exact O(n²) variant runs the full gradient loop as jitted
device steps (pairwise ops are MXU/VPU-friendly); the Barnes-Hut variant keeps
the reference's host-side tree approximation (irregular pointer-chasing that
XLA cannot tile) over numpy, with the same builder surface.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.quadtree import SPTree
from deeplearning4j_tpu.clustering.vptree import VPTree


def _binary_search_betas(d2: np.ndarray, perplexity: float, tol: float = 1e-5,
                         max_tries: int = 50) -> np.ndarray:
    """Per-point precision search so each conditional distribution hits the
    target perplexity (reference Tsne.hBeta loop)."""
    n = d2.shape[0]
    betas = np.ones(n)
    log_u = np.log(perplexity)
    P = np.zeros_like(d2)
    for i in range(n):
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        row = np.delete(d2[i], i)
        for _ in range(max_tries):
            p = np.exp(-row * beta)
            s = max(p.sum(), 1e-12)
            h = np.log(s) + beta * float((row * p).sum()) / s
            diff = h - log_u
            if abs(diff) < tol:
                break
            if diff > 0:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p = np.exp(-row * beta)
        P[i] = np.insert(p / max(p.sum(), 1e-12), i, 0.0)
        betas[i] = beta
    return P


class Tsne:
    """Exact t-SNE (reference plot/Tsne.java) with a jitted update loop."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, max_iter: int = 500,
                 momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 250,
                 stop_lying_iteration: int = 100, exaggeration: float = 12.0,
                 seed: int = 42):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.momentum = momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.seed = seed

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        d2 = ((x[:, None] - x[None]) ** 2).sum(-1)
        P = _binary_search_betas(d2, min(self.perplexity, (n - 1) / 3))
        P = (P + P.T) / (2 * n)
        P = np.maximum(P, 1e-12)

        rng = np.random.default_rng(self.seed)
        y0 = jnp.asarray(rng.normal(0, 1e-4, (n, self.n_components)))
        Pj = jnp.asarray(P)

        lr = self.learning_rate

        @jax.jit
        def grad_step(y, vel, gains, P_eff, mom):
            d = y[:, None] - y[None]                       # (n, n, c)
            num = 1.0 / (1.0 + (d ** 2).sum(-1))
            num = num * (1.0 - jnp.eye(y.shape[0]))
            Q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
            PQ = (P_eff - Q) * num                         # (n, n)
            g = 4.0 * jnp.einsum("ij,ijc->ic", PQ, d)
            same_sign = (g > 0) == (vel > 0)
            gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                             0.01, None)
            vel = mom * vel - lr * gains * g
            y = y + vel
            return y - y.mean(0), vel, gains

        y = y0
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        for it in range(self.max_iter):
            mom = (self.momentum if it < self.switch_momentum_iteration
                   else self.final_momentum)
            P_eff = Pj * self.exaggeration if it < self.stop_lying_iteration else Pj
            y, vel, gains = grad_step(y, vel, gains, P_eff, mom)
        return np.asarray(y)


class BarnesHutTsne:
    """theta-approximated t-SNE (reference plot/BarnesHutTsne.java:64).

    Builder mirrors the reference: setMaxIter, theta, perplexity,
    numDimension, etc.
    """

    def __init__(self, n_components: int = 2, theta: float = 0.5,
                 perplexity: float = 30.0, learning_rate: float = 200.0,
                 max_iter: int = 300, seed: int = 42):
        self.n_components = n_components
        self.theta = theta
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.max_iter = max_iter
        self.seed = seed
        self.embedding: Optional[np.ndarray] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def theta(self, t: float):
            self._kw["theta"] = t
            return self

        def perplexity(self, p: float):
            self._kw["perplexity"] = p
            return self

        def set_max_iter(self, n: int):
            self._kw["max_iter"] = n
            return self

        def num_dimension(self, d: int):
            self._kw["n_components"] = d
            return self

        def learning_rate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def build(self) -> "BarnesHutTsne":
            return BarnesHutTsne(**self._kw)

    @staticmethod
    def builder() -> "BarnesHutTsne.Builder":
        return BarnesHutTsne.Builder()

    def fit(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)  # lint: host-sync-in-hot-loop-ok (pure NumPy host algorithm; no device loop)
        n = x.shape[0]
        if self.theta <= 0 or n < 64:
            self.embedding = Tsne(
                n_components=self.n_components, perplexity=self.perplexity,
                learning_rate=self.learning_rate, max_iter=self.max_iter,
                seed=self.seed).fit_transform(x)
            return self.embedding

        # sparse input similarities from 3*perplexity nearest neighbors (VPTree)
        k = min(n - 1, int(3 * self.perplexity))
        tree = VPTree(x)
        rows, cols, d2 = [], [], []
        for i in range(n):
            nbrs = tree.knn(x[i], k + 1)
            for j, dist in nbrs:
                if j != i:
                    rows.append(i)
                    cols.append(j)
                    d2.append(dist * dist)
        rows = np.array(rows)
        cols = np.array(cols)
        d2 = np.array(d2)
        # per-row beta search on the sparse neighborhoods
        P = np.zeros(len(rows))
        log_u = np.log(min(self.perplexity, k))
        for i in range(n):
            sel = rows == i
            row = d2[sel]
            beta, bmin, bmax = 1.0, -np.inf, np.inf
            for _ in range(50):
                p = np.exp(-row * beta)
                s = max(p.sum(), 1e-12)
                h = np.log(s) + beta * (row * p).sum() / s
                diff = h - log_u
                if abs(diff) < 1e-5:
                    break
                if diff > 0:
                    bmin, beta = beta, (beta * 2 if bmax == np.inf else (beta + bmax) / 2)
                else:
                    bmax, beta = beta, (beta / 2 if bmin == -np.inf else (beta + bmin) / 2)
            p = np.exp(-row * beta)
            P[sel] = p / max(p.sum(), 1e-12)
        # symmetrize sparse P
        sym: dict = {}
        for r, c, v in zip(rows, cols, P):
            sym[(r, c)] = sym.get((r, c), 0.0) + v / (2 * n)
            sym[(c, r)] = sym.get((c, r), 0.0) + v / (2 * n)
        e_rows = np.array([rc[0] for rc in sym])
        e_cols = np.array([rc[1] for rc in sym])
        e_vals = np.array(list(sym.values()))

        rng = np.random.default_rng(self.seed)
        y = rng.normal(0, 1e-4, (n, self.n_components))
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        for it in range(self.max_iter):
            exag = 12.0 if it < min(100, self.max_iter // 3) else 1.0
            # attractive forces over the sparse edges
            d = y[e_rows] - y[e_cols]
            q_num = 1.0 / (1.0 + (d ** 2).sum(-1))
            w = (exag * e_vals * q_num)[:, None] * d
            pos_f = np.zeros_like(y)
            np.add.at(pos_f, e_rows, w)
            # repulsive forces via SPTree
            stree = SPTree(y)
            neg_f = np.zeros_like(y)
            z = 0.0
            for i in range(n):
                z += stree.compute_non_edge_forces(i, self.theta, neg_f[i])
            grad = pos_f - neg_f / max(z, 1e-12)
            same_sign = (grad > 0) == (vel > 0)
            gains = np.clip(np.where(same_sign, gains * 0.8, gains + 0.2), 0.01, None)
            mom = 0.5 if it < self.max_iter // 2 else 0.8
            vel = mom * vel - self.learning_rate * gains * grad
            y = y + vel
            y -= y.mean(0)
        self.embedding = y
        return y
