from deeplearning4j_tpu.plot.tsne import BarnesHutTsne, Tsne

__all__ = ["Tsne", "BarnesHutTsne"]
