"""Record -> DataSet conversion iterators (the DataVec bridge).

Reference: deeplearning4j-core datasets/datavec/RecordReaderDataSetIterator.java:52,
SequenceRecordReaderDataSetIterator.java, RecordReaderMultiDataSetIterator.java
(SURVEY.md §2.2) — the main ETL entry converting reader records into
(features, one-hot labels) minibatches, sequence pairs with optional
alignment, and named multi-input/multi-output sets.
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader


def _one_hot(idx: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((len(idx), n), np.float32)
    out[np.arange(len(idx)), idx.astype(int)] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """records -> DataSet batches. ``label_index`` column becomes the label
    (one-hot when ``num_classes`` given, else regression); remaining columns
    are features. ``label_index=None`` => all columns are features."""

    def __init__(self, reader: RecordReader, batch: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self.batch = batch
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression or num_classes is None
        self.label_index_to = label_index_to

    def __iter__(self) -> Iterator[DataSet]:
        feats: List[List[float]] = []
        labels: List = []
        for rec in self.reader:
            if self.label_index is None:
                feats.append([float(v) for v in rec])
                labels.append(0.0)
            elif self.label_index_to is not None:
                lo, hi = self.label_index, self.label_index_to
                labels.append([float(v) for v in rec[lo:hi + 1]])
                feats.append([float(v) for v in rec[:lo] + rec[hi + 1:]])
            else:
                li = self.label_index if self.label_index >= 0 else len(rec) - 1
                labels.append(float(rec[li]))
                feats.append([float(v) for v in rec[:li] + rec[li + 1:]])
            if len(feats) == self.batch:
                yield self._make(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._make(feats, labels)

    def _make(self, feats, labels) -> DataSet:
        x = np.asarray(feats, np.float32)
        if self.label_index is None:
            y = np.zeros((len(x), 0), np.float32)
        elif not self.regression and self.num_classes:
            y = _one_hot(np.asarray(labels), self.num_classes)
        else:
            y = np.asarray(labels, np.float32)
            if y.ndim == 1:
                y = y[:, None]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Paired feature/label sequence readers -> padded+masked sequence
    DataSets [B, T, F] (reference SequenceRecordReaderDataSetIterator with
    ALIGN_END-style padding via mask arrays). A single reader whose rows
    carry the label in ``label_index`` also works."""

    def __init__(self, features: RecordReader, batch: int,
                 labels: Optional[RecordReader] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index: Optional[int] = None):
        self.features = features
        self.labels = labels
        self.batch = batch
        self.num_classes = num_classes
        self.regression = regression or num_classes is None
        self.label_index = label_index

    def _pairs(self):
        fseqs = list(self.features.sequences()
                     if hasattr(self.features, "sequences")
                     else self.features)
        if self.labels is not None:
            lseqs = list(self.labels.sequences()
                         if hasattr(self.labels, "sequences") else self.labels)
        else:
            li = self.label_index if self.label_index is not None else -1
            lseqs = [[[r[li]] for r in seq] for seq in fseqs]
            fseqs = [[(r[:li] + r[li + 1:]) if li >= 0 else r[:-1]
                      for r in seq] for seq in fseqs]
        return fseqs, lseqs

    def __iter__(self) -> Iterator[DataSet]:
        fseqs, lseqs = self._pairs()
        for b0 in range(0, len(fseqs), self.batch):
            fb = fseqs[b0:b0 + self.batch]
            lb = lseqs[b0:b0 + self.batch]
            t_max = max(len(s) for s in fb)
            nf = len(fb[0][0])
            x = np.zeros((len(fb), t_max, nf), np.float32)
            mask = np.zeros((len(fb), t_max), np.float32)
            if self.regression:
                nl = len(lb[0][0])
                y = np.zeros((len(fb), t_max, nl), np.float32)
            else:
                y = np.zeros((len(fb), t_max, self.num_classes), np.float32)
            for i, (fs, ls) in enumerate(zip(fb, lb)):
                for t, row in enumerate(fs):
                    x[i, t] = np.asarray(row, np.float32)
                    mask[i, t] = 1.0
                for t, row in enumerate(ls):
                    if self.regression:
                        y[i, t] = np.asarray(row, np.float32)
                    else:
                        y[i, t, int(row[0])] = 1.0
            yield DataSet(x, y, features_mask=mask, labels_mask=mask.copy())


class RecordReaderMultiDataSetIterator:
    """Named multi-input/multi-output sets (reference
    RecordReaderMultiDataSetIterator builder): register readers by name, then
    declare inputs/outputs as column ranges over them."""

    def __init__(self, batch: int):
        self.batch = batch
        self._readers: Dict[str, RecordReader] = {}
        self._inputs: List = []
        self._outputs: List = []

    def add_reader(self, name: str, reader: RecordReader) -> "RecordReaderMultiDataSetIterator":
        self._readers[name] = reader
        return self

    def add_input(self, name: str, col_from: int = 0,
                  col_to: Optional[int] = None) -> "RecordReaderMultiDataSetIterator":
        self._inputs.append((name, col_from, col_to, None))
        return self

    def add_output(self, name: str, col_from: int = 0,
                   col_to: Optional[int] = None) -> "RecordReaderMultiDataSetIterator":
        self._outputs.append((name, col_from, col_to, None))
        return self

    def add_output_one_hot(self, name: str, column: int,
                           num_classes: int) -> "RecordReaderMultiDataSetIterator":
        self._outputs.append((name, column, column, num_classes))
        return self

    def __iter__(self):
        streams = {n: list(r) for n, r in self._readers.items()}
        n = min(len(v) for v in streams.values())
        for b0 in range(0, n, self.batch):
            ins = [self._slice(streams, spec, b0) for spec in self._inputs]
            outs = [self._slice(streams, spec, b0) for spec in self._outputs]
            yield ins, outs

    def _slice(self, streams, spec, b0) -> np.ndarray:
        name, lo, hi, one_hot = spec
        rows = streams[name][b0:b0 + self.batch]
        if one_hot is not None:
            idx = np.asarray([float(r[lo]) for r in rows])
            return _one_hot(idx, one_hot)
        sel = [[float(v) for v in (r[lo:hi + 1] if hi is not None else r[lo:])]
               for r in rows]
        return np.asarray(sel, np.float32)
