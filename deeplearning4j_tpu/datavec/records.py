"""RecordReader SPI: file -> record (list of values) streams.

Reference: the external DataVec library's readers as consumed by
deeplearning4j-core datasets/datavec/*.java (RecordReaderDataSetIterator:52
is the main ETL entry, SURVEY.md §2.2). Readers here produce plain Python
lists per record; numeric CSV parsing rides the native C++ fast path
(nativert.read_csv_numeric) when every field is numeric.
"""
from __future__ import annotations

import csv
import io
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np


class RecordReader:
    """One record per example: a list of values (str or float)."""

    def records(self) -> Iterator[List]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[List]:
        return self.records()

    def reset(self) -> None:
        pass


class CollectionRecordReader(RecordReader):
    """In-memory records (reference DataVec CollectionRecordReader)."""

    def __init__(self, collection: Iterable[Sequence]):
        self._records = [list(r) for r in collection]

    def records(self) -> Iterator[List]:
        return iter([list(r) for r in self._records])


class LineRecordReader(RecordReader):
    """One line per record, single string value."""

    def __init__(self, path: Union[str, Path]):
        self.path = Path(path)

    def records(self) -> Iterator[List]:
        with open(self.path) as f:
            for line in f:
                yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """Delimited text records (reference DataVec CSVRecordReader). Fields
    parse to float when possible, else stay strings. Fully numeric files use
    the native C++ CSV reader."""

    def __init__(self, path: Union[str, Path], skip_lines: int = 0,
                 delimiter: str = ","):
        self.path = Path(path)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _numeric_fast_path(self) -> Optional[np.ndarray]:
        # ONE native pass validates while parsing (strict mode): any
        # empty/non-numeric field or ragged row anywhere in the file returns
        # None — a single 'NA' deep in the file must not be silently coerced
        # to 0 — and the file routes through the general reader below.
        from deeplearning4j_tpu import nativert
        return nativert.read_csv_numeric(str(self.path), self.delimiter,
                                         self.skip_lines, strict=True)

    def records(self) -> Iterator[List]:
        fast = self._numeric_fast_path()
        if fast is not None:
            for row in fast:
                yield [float(v) for v in row]
            return
        with open(self.path, newline="") as f:
            rd = csv.reader(f, delimiter=self.delimiter)
            for i, row in enumerate(rd):
                if i < self.skip_lines or not row:
                    continue
                yield [_maybe_float(v) for v in row]


class CSVSequenceRecordReader(RecordReader):
    """One file per sequence, rows are timesteps (reference DataVec
    CSVSequenceRecordReader). ``records`` yields one sequence (list of rows)
    per file, in sorted path order."""

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]],
                 skip_lines: int = 0, delimiter: str = ","):
        if isinstance(paths, (str, Path)) and Path(paths).is_dir():
            self.paths = sorted(Path(paths).glob("*.csv")) or sorted(
                p for p in Path(paths).iterdir() if p.is_file())
        elif isinstance(paths, (str, Path)):
            self.paths = [Path(paths)]
        else:
            self.paths = [Path(p) for p in paths]
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def sequences(self) -> Iterator[List[List]]:
        for p in self.paths:
            rows = []
            with open(p, newline="") as f:
                rd = csv.reader(f, delimiter=self.delimiter)
                for i, row in enumerate(rd):
                    if i < self.skip_lines or not row:
                        continue
                    rows.append([_maybe_float(v) for v in row])
            yield rows

    def records(self) -> Iterator[List]:
        return self.sequences()


class ImageRecordReader(RecordReader):
    """Image files -> flattened pixel records + directory-name label index
    (reference DataVec ImageRecordReader as used for LFW). Labels come from
    the parent directory name of each file."""

    def __init__(self, root: Union[str, Path], height: int, width: int,
                 channels: int = 3,
                 extensions: Sequence[str] = (".png", ".jpg", ".jpeg",
                                              ".bmp", ".gif")):
        self.root = Path(root)
        self.height, self.width, self.channels = height, width, channels
        self.files = sorted(p for p in self.root.rglob("*")
                            if p.suffix.lower() in extensions)
        self.labels = sorted({p.parent.name for p in self.files})
        self._label_index = {l: i for i, l in enumerate(self.labels)}

    def num_labels(self) -> int:
        return len(self.labels)

    def _load(self, path: Path) -> np.ndarray:
        from PIL import Image
        img = Image.open(path)
        img = img.convert("RGB" if self.channels == 3 else "L")
        img = img.resize((self.width, self.height))
        arr = np.asarray(img, np.float32) / 255.0
        if self.channels == 1 and arr.ndim == 2:
            arr = arr[..., None]
        return arr

    def records(self) -> Iterator[List]:
        for p in self.files:
            arr = self._load(p).ravel()
            yield [*arr.tolist(), float(self._label_index[p.parent.name])]


def _maybe_float(v: str):
    try:
        return float(v)
    except ValueError:
        return v
