"""DataVec bridge: record readers + record->DataSet iterators (SURVEY.md §2.2)."""
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, LineRecordReader, RecordReader,
)
from deeplearning4j_tpu.datavec.iterators import (
    RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "CollectionRecordReader", "CSVRecordReader", "CSVSequenceRecordReader",
    "ImageRecordReader", "LineRecordReader", "RecordReader",
    "RecordReaderDataSetIterator", "RecordReaderMultiDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
]
