"""Minimal HDF5 reader/writer over the system C library via ctypes.

The reference reaches HDF5 natively through JavaCPP (`Loader.load(hdf5.class)`,
reference deeplearning4j-modelimport keras/KerasModelImport.java:64). This
binding goes to ``libhdf5_serial`` directly via ctypes to mirror that
native-first design and keep the import path dependency-free (h5py does exist
in this image; tests use it as an independent cross-check of this reader).
Covers exactly what Keras archives need: groups,
float/int datasets, scalar string attributes and string-array attributes
(fixed- and variable-length), plus writing the same so tests can produce
fixtures and models can be exported.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import functools
import threading
from typing import Dict, List, Optional, Union

import numpy as np

# The Debian libhdf5_serial build is NOT thread-safe; every libhdf5 call in
# this module runs under one process-wide lock (the gateway server calls in
# from handler threads).
_h5_lock = threading.RLock()


def _locked(fn):
    @functools.wraps(fn)
    def wrapper(*a, **kw):
        with _h5_lock:
            return fn(*a, **kw)
    return wrapper

hid_t = ctypes.c_int64
herr_t = ctypes.c_int
hsize_t = ctypes.c_uint64
htri_t = ctypes.c_int

H5F_ACC_RDONLY = 0
H5F_ACC_TRUNC = 2
H5P_DEFAULT = 0
H5S_ALL = 0
H5S_SCALAR = 0
H5_INDEX_NAME = 0
H5_ITER_INC = 0
H5T_DIR_ASCEND = 1
H5T_VARIABLE = ctypes.c_size_t(-1).value
# H5T_class_t
H5T_INTEGER, H5T_FLOAT, H5T_STRING = 0, 1, 3
H5T_SGN_NONE = 0

_LIB_CANDIDATES = [
    "libhdf5_serial.so.103", "libhdf5_serial.so", "libhdf5.so.103",
    "libhdf5.so.200", "libhdf5.so",
]

_lib: Optional[ctypes.CDLL] = None
_types: Dict[str, int] = {}


class _H5GInfo(ctypes.Structure):
    _fields_ = [("storage_type", ctypes.c_int), ("nlinks", hsize_t),
                ("max_corder", ctypes.c_int64), ("mounted", ctypes.c_int)]


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = None
    names = list(_LIB_CANDIDATES)
    found = ctypes.util.find_library("hdf5_serial") or ctypes.util.find_library("hdf5")
    if found:
        names.insert(0, found)
    for name in names:
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    if lib is None:
        raise RuntimeError("libhdf5 not found on this system")

    lib.H5open.restype = herr_t
    lib.H5open()
    # Failed probes (exists/open) are part of normal control flow here; leave
    # no entries on the auto error stack — accumulated error-message ids
    # otherwise trip "infinite loop closing library" in H5close at exit.
    lib.H5Eset_auto2.restype = herr_t
    lib.H5Eset_auto2.argtypes = [hid_t, ctypes.c_void_p, ctypes.c_void_p]
    lib.H5Eset_auto2(0, None, None)

    def sig(name, restype, argtypes):
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
        return fn

    sig("H5Fopen", hid_t, [ctypes.c_char_p, ctypes.c_uint, hid_t])
    sig("H5Fcreate", hid_t, [ctypes.c_char_p, ctypes.c_uint, hid_t, hid_t])
    sig("H5Fclose", herr_t, [hid_t])
    sig("H5Gopen2", hid_t, [hid_t, ctypes.c_char_p, hid_t])
    sig("H5Gcreate2", hid_t, [hid_t, ctypes.c_char_p, hid_t, hid_t, hid_t])
    sig("H5Gget_info", herr_t, [hid_t, ctypes.POINTER(_H5GInfo)])
    sig("H5Gclose", herr_t, [hid_t])
    sig("H5Lexists", htri_t, [hid_t, ctypes.c_char_p, hid_t])
    sig("H5Lget_name_by_idx", ctypes.c_ssize_t,
        [hid_t, ctypes.c_char_p, ctypes.c_int, ctypes.c_int, hsize_t,
         ctypes.c_char_p, ctypes.c_size_t, hid_t])
    sig("H5Oopen", hid_t, [hid_t, ctypes.c_char_p, hid_t])
    sig("H5Oclose", herr_t, [hid_t])
    sig("H5Dopen2", hid_t, [hid_t, ctypes.c_char_p, hid_t])
    sig("H5Dcreate2", hid_t,
        [hid_t, ctypes.c_char_p, hid_t, hid_t, hid_t, hid_t, hid_t])
    sig("H5Dget_space", hid_t, [hid_t])
    sig("H5Dget_type", hid_t, [hid_t])
    sig("H5Dread", herr_t, [hid_t, hid_t, hid_t, hid_t, hid_t, ctypes.c_void_p])
    sig("H5Dwrite", herr_t, [hid_t, hid_t, hid_t, hid_t, hid_t, ctypes.c_void_p])
    sig("H5Dclose", herr_t, [hid_t])
    sig("H5Screate", hid_t, [ctypes.c_int])
    sig("H5Screate_simple", hid_t,
        [ctypes.c_int, ctypes.POINTER(hsize_t), ctypes.POINTER(hsize_t)])
    sig("H5Sget_simple_extent_ndims", ctypes.c_int, [hid_t])
    sig("H5Sget_simple_extent_dims", ctypes.c_int,
        [hid_t, ctypes.POINTER(hsize_t), ctypes.POINTER(hsize_t)])
    sig("H5Sget_simple_extent_npoints", ctypes.c_int64, [hid_t])
    sig("H5Sclose", herr_t, [hid_t])
    sig("H5Aexists", htri_t, [hid_t, ctypes.c_char_p])
    sig("H5Adelete", herr_t, [hid_t, ctypes.c_char_p])
    sig("H5Aopen", hid_t, [hid_t, ctypes.c_char_p, hid_t])
    sig("H5Acreate2", hid_t, [hid_t, ctypes.c_char_p, hid_t, hid_t, hid_t, hid_t])
    sig("H5Aget_type", hid_t, [hid_t])
    sig("H5Aget_space", hid_t, [hid_t])
    sig("H5Aread", herr_t, [hid_t, hid_t, ctypes.c_void_p])
    sig("H5Awrite", herr_t, [hid_t, hid_t, ctypes.c_void_p])
    sig("H5Aclose", herr_t, [hid_t])
    sig("H5Tcopy", hid_t, [hid_t])
    sig("H5Tset_size", herr_t, [hid_t, ctypes.c_size_t])
    sig("H5Tget_size", ctypes.c_size_t, [hid_t])
    sig("H5Tget_class", ctypes.c_int, [hid_t])
    sig("H5Tget_sign", ctypes.c_int, [hid_t])
    sig("H5Tis_variable_str", htri_t, [hid_t])
    sig("H5Tget_native_type", hid_t, [hid_t, ctypes.c_int])
    sig("H5Tclose", herr_t, [hid_t])
    try:
        sig("H5free_memory", herr_t, [ctypes.c_void_p])
    # lint: swallowed-exception-ok (symbol optional in older libhdf5; callers guard on hasattr)
    except AttributeError:
        pass

    for pyname, gname in [
        ("c_s1", "H5T_C_S1_g"),
        ("f32", "H5T_NATIVE_FLOAT_g"), ("f64", "H5T_NATIVE_DOUBLE_g"),
        ("i8", "H5T_NATIVE_SCHAR_g"), ("u8", "H5T_NATIVE_UCHAR_g"),
        ("i16", "H5T_NATIVE_SHORT_g"), ("u16", "H5T_NATIVE_USHORT_g"),
        ("i32", "H5T_NATIVE_INT_g"), ("u32", "H5T_NATIVE_UINT_g"),
        ("i64", "H5T_NATIVE_LLONG_g"), ("u64", "H5T_NATIVE_ULLONG_g"),
    ]:
        _types[pyname] = hid_t.in_dll(lib, gname).value
    _lib = lib
    return lib


def hdf5_available() -> bool:
    try:
        _load()
        return True
    except (RuntimeError, OSError):
        return False


_NP_TO_H5 = {
    np.dtype(np.float32): "f32", np.dtype(np.float64): "f64",
    np.dtype(np.int8): "i8", np.dtype(np.uint8): "u8",
    np.dtype(np.int16): "i16", np.dtype(np.uint16): "u16",
    np.dtype(np.int32): "i32", np.dtype(np.uint32): "u32",
    np.dtype(np.int64): "i64", np.dtype(np.uint64): "u64",
}


def _native_np_dtype(lib, type_id) -> np.dtype:
    cls = lib.H5Tget_class(type_id)
    size = lib.H5Tget_size(type_id)
    if cls == H5T_FLOAT and size in (4, 8):
        dt = np.dtype(np.float64 if size == 8 else np.float32)
    elif cls == H5T_INTEGER and size in (1, 2, 4, 8):
        unsigned = lib.H5Tget_sign(type_id) == H5T_SGN_NONE
        dt = np.dtype(f"{'u' if unsigned else 'i'}{size}")
    else:
        raise ValueError(
            f"unsupported HDF5 type (class {cls}, {size} bytes) — supported: "
            "f32/f64 and 1/2/4/8-byte integers")
    if dt not in _NP_TO_H5:
        raise ValueError(f"unsupported HDF5-mapped dtype {dt}")
    return dt


class H5File:
    """Tiny h5py-shaped facade over the C library. Paths are '/'-separated."""

    @_locked
    def __init__(self, path: str, mode: str = "r"):
        self._lib = _load()
        if mode == "r":
            self._fid = self._lib.H5Fopen(str(path).encode(), H5F_ACC_RDONLY,
                                          H5P_DEFAULT)
        elif mode == "w":
            self._fid = self._lib.H5Fcreate(str(path).encode(), H5F_ACC_TRUNC,
                                            H5P_DEFAULT, H5P_DEFAULT)
        else:
            raise ValueError("mode must be 'r' or 'w'")
        if self._fid < 0:
            raise OSError(f"cannot open HDF5 file {path!r} (mode={mode})")

    # ------------------------------------------------------------------ lifecycle
    @_locked
    def close(self) -> None:
        if getattr(self, "_fid", -1) >= 0:
            self._lib.H5Fclose(self._fid)
            self._fid = -1

    def __enter__(self) -> "H5File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        # lint: swallowed-exception-ok (destructor must not raise during interpreter teardown)
        except Exception:
            pass

    # ------------------------------------------------------------------ reading
    @_locked
    def exists(self, path: str) -> bool:
        # every intermediate link must exist too, else H5Lexists errors
        parts = [p for p in path.strip("/").split("/") if p]
        sofar = ""
        for p in parts:
            sofar += "/" + p
            if self._lib.H5Lexists(self._fid, sofar.encode(), H5P_DEFAULT) <= 0:
                return False
        return True

    @_locked
    def list_group(self, path: str = "/") -> List[str]:
        gid = self._lib.H5Gopen2(self._fid, path.encode(), H5P_DEFAULT)
        if gid < 0:
            raise KeyError(f"no such group: {path}")
        try:
            info = _H5GInfo()
            self._lib.H5Gget_info(gid, ctypes.byref(info))
            names = []
            for i in range(info.nlinks):
                n = self._lib.H5Lget_name_by_idx(
                    gid, b".", H5_INDEX_NAME, H5_ITER_INC, i, None, 0,
                    H5P_DEFAULT)
                buf = ctypes.create_string_buffer(n + 1)
                self._lib.H5Lget_name_by_idx(
                    gid, b".", H5_INDEX_NAME, H5_ITER_INC, i, buf, n + 1,
                    H5P_DEFAULT)
                names.append(buf.value.decode())
            return names
        finally:
            self._lib.H5Gclose(gid)

    @_locked
    def read_dataset(self, path: str) -> np.ndarray:
        lib = self._lib
        did = lib.H5Dopen2(self._fid, path.encode(), H5P_DEFAULT)
        if did < 0:
            raise KeyError(f"no such dataset: {path}")
        try:
            sid = lib.H5Dget_space(did)
            ndim = lib.H5Sget_simple_extent_ndims(sid)
            dims = (hsize_t * max(ndim, 1))()
            if ndim > 0:
                lib.H5Sget_simple_extent_dims(sid, dims, None)
            shape = tuple(int(dims[i]) for i in range(ndim))
            lib.H5Sclose(sid)
            tid = lib.H5Dget_type(did)
            ntid = lib.H5Tget_native_type(tid, H5T_DIR_ASCEND)
            dt = _native_np_dtype(lib, ntid)
            lib.H5Tclose(ntid)
            lib.H5Tclose(tid)
            out = np.empty(shape if shape else (), dt)
            if lib.H5Dread(did, _types[_NP_TO_H5[dt]], H5S_ALL, H5S_ALL,
                           H5P_DEFAULT,
                           out.ctypes.data_as(ctypes.c_void_p)) < 0:
                raise OSError(f"H5Dread failed for {path}")
            return out
        finally:
            lib.H5Dclose(did)

    def _read_attr_handle(self, aid) -> Union[str, List[str], np.ndarray]:
        lib = self._lib
        tid = lib.H5Aget_type(aid)
        sid = lib.H5Aget_space(aid)
        try:
            npoints = int(lib.H5Sget_simple_extent_npoints(sid))
            cls = lib.H5Tget_class(tid)
            if cls == H5T_STRING:
                if lib.H5Tis_variable_str(tid) > 0:
                    # c_void_p (not c_char_p) so the library-allocated
                    # pointers survive ctypes' bytes auto-conversion and can
                    # be returned to libhdf5 — without H5free_memory every
                    # vlen read leaks, which adds up in the long-lived
                    # keras_server process
                    bufs = (ctypes.c_void_p * npoints)()
                    mem = lib.H5Tcopy(_types["c_s1"])
                    lib.H5Tset_size(mem, H5T_VARIABLE)
                    lib.H5Aread(aid, mem, bufs)
                    vals = []
                    free = getattr(lib, "H5free_memory", None)
                    for i in range(npoints):
                        p = bufs[i]
                        s = (ctypes.cast(p, ctypes.c_char_p).value or b"") \
                            if p else b""
                        vals.append(s.decode("utf-8", "replace"))
                        if p and free is not None:
                            free(ctypes.c_void_p(p))
                    lib.H5Tclose(mem)
                else:
                    size = lib.H5Tget_size(tid)
                    raw = ctypes.create_string_buffer(size * npoints)
                    lib.H5Aread(aid, tid, raw)
                    vals = [raw.raw[i * size:(i + 1) * size]
                            .split(b"\x00")[0].decode("utf-8", "replace")
                            for i in range(npoints)]
                return vals[0] if npoints == 1 else vals
            ntid = lib.H5Tget_native_type(tid, H5T_DIR_ASCEND)
            dt = _native_np_dtype(lib, ntid)
            lib.H5Tclose(ntid)
            out = np.empty((npoints,), dt)
            lib.H5Aread(aid, _types[_NP_TO_H5[dt]],
                        out.ctypes.data_as(ctypes.c_void_p))
            return out[0] if npoints == 1 else out
        finally:
            lib.H5Sclose(sid)
            lib.H5Tclose(tid)

    @_locked
    def read_attr(self, obj_path: str, name: str):
        lib = self._lib
        oid = lib.H5Oopen(self._fid, obj_path.encode(), H5P_DEFAULT)
        if oid < 0:
            raise KeyError(f"no such object: {obj_path}")
        try:
            if lib.H5Aexists(oid, name.encode()) <= 0:
                raise KeyError(f"no attribute {name!r} on {obj_path}")
            aid = lib.H5Aopen(oid, name.encode(), H5P_DEFAULT)
            try:
                return self._read_attr_handle(aid)
            finally:
                lib.H5Aclose(aid)
        finally:
            lib.H5Oclose(oid)

    @_locked
    def has_attr(self, obj_path: str, name: str) -> bool:
        lib = self._lib
        oid = lib.H5Oopen(self._fid, obj_path.encode(), H5P_DEFAULT)
        if oid < 0:
            return False
        try:
            return lib.H5Aexists(oid, name.encode()) > 0
        finally:
            lib.H5Oclose(oid)

    # ------------------------------------------------------------------ writing
    @_locked
    def create_group(self, path: str) -> None:
        parts = [p for p in path.strip("/").split("/") if p]
        sofar = ""
        for p in parts:
            sofar += "/" + p
            if self._lib.H5Lexists(self._fid, sofar.encode(), H5P_DEFAULT) <= 0:
                gid = self._lib.H5Gcreate2(self._fid, sofar.encode(),
                                           H5P_DEFAULT, H5P_DEFAULT, H5P_DEFAULT)
                if gid < 0:
                    raise OSError(f"cannot create group {sofar}")
                self._lib.H5Gclose(gid)

    @_locked
    def write_dataset(self, path: str, arr: np.ndarray) -> None:
        lib = self._lib
        arr = np.ascontiguousarray(arr)
        if arr.dtype not in _NP_TO_H5:
            arr = arr.astype(np.float32)
        parent = path.rsplit("/", 1)[0]
        if parent and parent != path:
            self.create_group(parent)
        dims = (hsize_t * max(arr.ndim, 1))(*arr.shape) if arr.ndim else None
        sid = (lib.H5Screate_simple(arr.ndim, dims, None) if arr.ndim
               else lib.H5Screate(H5S_SCALAR))
        tid = _types[_NP_TO_H5[arr.dtype]]
        did = lib.H5Dcreate2(self._fid, path.encode(), tid, sid, H5P_DEFAULT,
                             H5P_DEFAULT, H5P_DEFAULT)
        if did < 0:
            lib.H5Sclose(sid)
            raise OSError(f"cannot create dataset {path}")
        try:
            if lib.H5Dwrite(did, tid, H5S_ALL, H5S_ALL, H5P_DEFAULT,
                            arr.ctypes.data_as(ctypes.c_void_p)) < 0:
                raise OSError(f"H5Dwrite failed for {path}")
        finally:
            lib.H5Dclose(did)
            lib.H5Sclose(sid)

    @_locked
    def write_attr(self, obj_path: str, name: str,
                   value: Union[str, List[str], np.ndarray, int, float]) -> None:
        """Strings are written as fixed-length null-padded ASCII (the Keras-1/
        h5py-2 convention the reference's importer reads)."""
        lib = self._lib
        oid = lib.H5Oopen(self._fid, obj_path.encode(), H5P_DEFAULT)
        if oid < 0:
            raise KeyError(f"no such object: {obj_path}")
        try:
            # overwrite semantics: replace an existing attribute
            if lib.H5Aexists(oid, name.encode()) > 0:
                lib.H5Adelete(oid, name.encode())
            if isinstance(value, str):
                value = [value]
                scalar = True
            elif isinstance(value, list) and all(isinstance(v, str) for v in value):
                scalar = False
            else:
                arr = np.atleast_1d(np.asarray(value))
                if arr.dtype not in _NP_TO_H5:
                    arr = arr.astype(np.float64)
                dims = (hsize_t * 1)(arr.size)
                sid = lib.H5Screate_simple(1, dims, None)
                tid = _types[_NP_TO_H5[arr.dtype]]
                aid = lib.H5Acreate2(oid, name.encode(), tid, sid, H5P_DEFAULT,
                                     H5P_DEFAULT)
                ok = aid >= 0 and lib.H5Awrite(
                    aid, tid, arr.ctypes.data_as(ctypes.c_void_p)) >= 0
                if aid >= 0:
                    lib.H5Aclose(aid)
                lib.H5Sclose(sid)
                if not ok:
                    raise OSError(f"cannot write attribute {name!r} on "
                                  f"{obj_path}")
                return
            enc = [v.encode() for v in value]
            size = max(max((len(e) for e in enc), default=0) + 1, 1)
            mem = lib.H5Tcopy(_types["c_s1"])
            lib.H5Tset_size(mem, size)
            buf = b"".join(e.ljust(size, b"\x00") for e in enc)
            if scalar:
                sid = lib.H5Screate(H5S_SCALAR)
            else:
                dims = (hsize_t * 1)(len(enc))
                sid = lib.H5Screate_simple(1, dims, None)
            aid = lib.H5Acreate2(oid, name.encode(), mem, sid, H5P_DEFAULT,
                                 H5P_DEFAULT)
            ok = aid >= 0 and lib.H5Awrite(aid, mem,
                                           ctypes.c_char_p(buf)) >= 0
            if aid >= 0:
                lib.H5Aclose(aid)
            lib.H5Sclose(sid)
            lib.H5Tclose(mem)
            if not ok:
                raise OSError(f"cannot write attribute {name!r} on {obj_path}")
        finally:
            lib.H5Oclose(oid)
