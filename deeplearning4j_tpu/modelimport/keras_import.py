"""Keras 1.x model import: HDF5 archive -> TPU-native network + weights.

Reference: deeplearning4j-modelimport keras/KerasModelImport.java:60
(`importKerasModelAndWeights`:85 -> ComputationGraph,
`importKerasSequentialModelAndWeights`:110 -> MultiLayerNetwork) and
keras/KerasLayer.java:39-52 — the supported layer set there is Input,
Activation, Dropout, Dense, TimeDistributedDense, LSTM, Convolution2D,
MaxPooling2D, AveragePooling2D, Flatten, Reshape, RepeatVector, Merge,
BatchNormalization (+ loss pseudo-layer :125). This importer covers the same
set (plus Embedding) and the Keras-1 weight layouts with TH/TF dim-ordering
fixes (KerasModel weight-copy logic).

Layout note: this framework is NHWC-native (XLA:TPU preferred). TH-ordered
Keras kernels (nb_filter, stack, rows, cols) are transposed into HWIO at
import; imported networks therefore take NHWC inputs regardless of the
original dim_ordering.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.modelimport.hdf5 import H5File
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, LSTM, OutputLayer, RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.vertices import ElementWiseVertex, MergeVertex

_ACTIVATIONS = {
    "linear": "identity", "hard_sigmoid": "hardsigmoid",
    "softmax": "softmax", "relu": "relu", "tanh": "tanh",
    "sigmoid": "sigmoid", "softplus": "softplus", "softsign": "softsign",
    "elu": "elu", "selu": "selu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge", "squared_hinge": "squaredhinge",
    "kullback_leibler_divergence": "kld",
    "poisson": "poisson",
}

_SUPPORTED = {
    "InputLayer", "Activation", "Dropout", "Dense", "TimeDistributedDense",
    "LSTM", "Convolution2D", "MaxPooling2D", "AveragePooling2D", "Flatten",
    "Reshape", "RepeatVector", "Merge", "BatchNormalization", "Embedding",
}


class InvalidKerasConfigurationException(ValueError):
    """Reference exceptions/InvalidKerasConfigurationException equivalent."""


def _act(name: Optional[str]) -> str:
    if not name:
        return "identity"
    return _ACTIVATIONS.get(name, name)


def _input_type_from_shape(shape: List[Optional[int]],
                           dim_ordering: str) -> InputType:
    """batch_input_shape (leading None stripped) -> InputType."""
    dims = [int(d) for d in shape if d is not None]
    if len(dims) == 3:
        if dim_ordering == "th":
            c, h, w = dims
        else:
            h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 1:
        return InputType.feed_forward(dims[0])
    raise InvalidKerasConfigurationException(
        f"unsupported input shape {shape}")


def _keras_layers(model_config: dict) -> List[dict]:
    cfg = model_config["config"]
    return cfg if isinstance(cfg, list) else cfg["layers"]


class _SequentialParse:
    def __init__(self):
        self.layers: List = []
        # keras layer name -> our layer index (weight-bearing layers only)
        self.index_of: Dict[str, int] = {}
        self.input_type: Optional[InputType] = None
        self.class_of: Dict[str, str] = {}


def _parse_sequential(model_config: dict, loss: Optional[str]) -> _SequentialParse:
    out = _SequentialParse()
    klayers = _keras_layers(model_config)
    pending_n: Optional[int] = None  # RepeatVector handled via preprocessor-less repeat

    for pos, kl in enumerate(klayers):
        cls = kl["class_name"]
        cfg = kl.get("config", {})
        name = cfg.get("name", f"layer_{pos}")
        if cls not in _SUPPORTED:
            raise InvalidKerasConfigurationException(
                f"unsupported Keras layer type {cls!r} (supported: "
                f"{sorted(_SUPPORTED)})")
        out.class_of[name] = cls
        if out.input_type is None and "batch_input_shape" in cfg:
            out.input_type = _input_type_from_shape(
                cfg["batch_input_shape"][1:], cfg.get("dim_ordering", "tf"))
        elif out.input_type is None and "input_dim" in cfg and cfg["input_dim"]:
            out.input_type = InputType.feed_forward(int(cfg["input_dim"]))

        last = pos == len(klayers) - 1
        lyr = _to_layer(cls, cfg, last=last, loss=loss)
        if lyr is None:
            continue  # shape-only layer (Input/Flatten/Reshape)
        out.index_of[name] = len(out.layers)
        out.layers.append(lyr)
    if not out.layers:
        raise InvalidKerasConfigurationException("model has no layers")
    # Dense followed by a trailing Activation (the Keras idiom
    # Dense(linear) + Activation(softmax)) folds into one OutputLayer so the
    # network ends in a loss-bearing layer (reference KerasLayer loss
    # pseudo-layer handling).
    if (isinstance(out.layers[-1], ActivationLayer)
            and len(out.layers) >= 2
            and type(out.layers[-2]) is DenseLayer):
        act = out.layers[-1].activation or "identity"
        dense = out.layers[-2]
        lloss = loss or ("mcxent" if act == "softmax" else "mse")
        merged = OutputLayer(n_out=dense.n_out, activation=act, loss=lloss)
        out.layers = out.layers[:-2] + [merged]
        out.index_of = {n: (i if i < len(out.layers) - 1 else
                            len(out.layers) - 1)
                        for n, i in out.index_of.items()
                        if i < len(out.layers) + 1}
    return out


def _to_layer(cls: str, cfg: dict, *, last: bool, loss: Optional[str]):
    """One Keras layer dict -> our layer config (or None for shape-only)."""
    act = _act(cfg.get("activation"))
    if cls in ("InputLayer", "Flatten", "Reshape", "RepeatVector"):
        # Rank adaptation is preprocessor territory; our builder auto-inserts
        # preprocessors from InputType inference (reference inserts
        # CnnToFeedForwardPreProcessor for Flatten the same way).
        return None
    if cls in ("Dense", "TimeDistributedDense"):
        n_out = int(cfg["output_dim"])
        if last:
            lloss = loss or ("mcxent" if act == "softmax" else "mse")
            klass = RnnOutputLayer if cls == "TimeDistributedDense" else OutputLayer
            return klass(n_out=n_out, activation=act, loss=lloss)
        return DenseLayer(n_out=n_out, activation=act)
    if cls == "Activation":
        return ActivationLayer(activation=act)
    if cls == "Dropout":
        return DropoutLayer(dropout=1.0 - float(cfg.get("p", 0.5)))
    if cls == "Convolution2D":
        border = cfg.get("border_mode", "valid")
        sub = cfg.get("subsample", [1, 1])
        return ConvolutionLayer(
            n_out=int(cfg["nb_filter"]),
            kernel_size=(int(cfg["nb_row"]), int(cfg["nb_col"])),
            stride=(int(sub[0]), int(sub[1])),
            convolution_mode="same" if border == "same" else "truncate",
            activation=act)
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        pool = cfg.get("pool_size", [2, 2])
        strides = cfg.get("strides") or pool
        border = cfg.get("border_mode", "valid")
        return SubsamplingLayer(
            pooling_type="max" if cls == "MaxPooling2D" else "avg",
            kernel_size=(int(pool[0]), int(pool[1])),
            stride=(int(strides[0]), int(strides[1])),
            convolution_mode="same" if border == "same" else "truncate")
    if cls == "LSTM":
        return LSTM(n_out=int(cfg["output_dim"]),
                    activation=_act(cfg.get("activation", "tanh")),
                    gate_activation=_act(cfg.get("inner_activation",
                                                 "hard_sigmoid")),
                    peephole=False)
    if cls == "BatchNormalization":
        # Keras BN applies no activation; pin identity so the network-level
        # default (sigmoid in the reference's GlobalConf) doesn't leak in.
        return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                  decay=float(cfg.get("momentum", 0.99)),
                                  activation="identity")
    if cls == "Embedding":
        return EmbeddingLayer(n_in=int(cfg["input_dim"]),
                              n_out=int(cfg["output_dim"]))
    raise InvalidKerasConfigurationException(f"unhandled layer {cls}")


# ---------------------------------------------------------------------------
# Weight copy
# ---------------------------------------------------------------------------

def _weight_root(f: H5File) -> str:
    return "/model_weights" if f.exists("/model_weights") else "/"


def _as_list(v) -> List[str]:
    return [v] if isinstance(v, str) else list(v)


def _layer_weights(f: H5File, root: str, lname: str):
    """Read a layer's weight arrays. Returns (names, arrays) — names preserve
    the archive's `weight_names` attribute so gate mapping can key on name
    suffixes instead of trusting array order (reference KerasLayer maps
    weights by name, keras/KerasLayer.java)."""
    g = f"{root.rstrip('/')}/{lname}"
    if not f.has_attr(g, "weight_names"):
        return [], []
    names = _as_list(f.read_attr(g, "weight_names"))
    out = []
    for wn in names:
        # weight_names may be bare ("dense_1_W") or nested ("dense_1/dense_1_W")
        p = f"{g}/{wn}" if f.exists(f"{g}/{wn}") else f"{root.rstrip('/')}/{wn}"
        out.append(f.read_dataset(p))
    return names, out


# Keras-1 canonical per-gate array suffixes, in the serialization order a
# canonical archive uses (input, cell, forget, output gates).
_LSTM_SUFFIXES = ("W_i", "U_i", "b_i", "W_c", "U_c", "b_c",
                  "W_f", "U_f", "b_f", "W_o", "U_o", "b_o")


def _convert_lstm(ws: List[np.ndarray],
                  names: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
    """Keras-1 LSTM weights -> fused {W [in,4H], RW [H,4H], b [4H]} in this
    framework's gate order (input, forget, cell, output).

    Arrays are matched by their `weight_names` suffix (``*_W_i``, ``*_U_c``,
    ...) so an archive whose weight_names order deviates from the canonical
    [i, c, f, o] listing still imports with the right gates; purely
    positional matching is the fallback when names are absent or don't look
    like Keras-1 gate names."""
    if len(ws) != 12:
        raise InvalidKerasConfigurationException(
            f"expected 12 LSTM weight arrays (Keras 1 layout), got {len(ws)}")
    by_suffix = {}
    if names and len(names) == 12:
        stripped = [str(n).split("/")[-1] for n in names]
        for suf in _LSTM_SUFFIXES:
            hits = [i for i, n in enumerate(stripped)
                    if n == suf or n.endswith("_" + suf)]
            if len(hits) == 1:
                by_suffix[suf] = ws[hits[0]]
    if len(by_suffix) == 12:
        ordered = [by_suffix[s] for s in _LSTM_SUFFIXES]
    else:  # positional fallback: canonical Keras-1 ordering
        ordered = ws
    wi, ui, bi, wc, uc, bc, wf, uf, bf, wo, uo, bo = ordered
    return {
        "W": np.concatenate([wi, wf, wc, wo], axis=1),
        "RW": np.concatenate([ui, uf, uc, uo], axis=1),
        "b": np.concatenate([bi, bf, bc, bo]),
    }


def _convert_conv(w: np.ndarray, dim_ordering: str) -> np.ndarray:
    if w.ndim == 4 and dim_ordering == "th":
        # (nb_filter, stack, rows, cols) -> (rows, cols, stack, nb_filter)
        return np.transpose(w, (2, 3, 1, 0))
    return w  # tf ordering == HWIO already


def _set_layer_params(cls: str, cfg: dict, params: dict, state: dict,
                      ws: List[np.ndarray],
                      names: Optional[List[str]] = None) -> None:
    if not ws:
        return
    if cls in ("Dense", "TimeDistributedDense", "Embedding"):
        params["W"] = jnp.asarray(ws[0], jnp.float32)
        if len(ws) > 1:
            params["b"] = jnp.asarray(ws[1], jnp.float32)
        elif "b" in params:
            params["b"] = jnp.zeros_like(params["b"])
    elif cls == "Convolution2D":
        # default must agree with _input_type_from_shape's default ("tf") so
        # a config missing the key gets one consistent interpretation
        params["W"] = jnp.asarray(
            _convert_conv(ws[0], cfg.get("dim_ordering", "tf")), jnp.float32)
        if len(ws) > 1:
            params["b"] = jnp.asarray(ws[1], jnp.float32)
    elif cls == "LSTM":
        for k, v in _convert_lstm(ws, names).items():
            params[k] = jnp.asarray(v, jnp.float32)
    elif cls == "BatchNormalization":
        params["gamma"] = jnp.asarray(ws[0], jnp.float32)
        params["beta"] = jnp.asarray(ws[1], jnp.float32)
        if len(ws) > 2:
            state["mean"] = jnp.asarray(ws[2], jnp.float32)
        if len(ws) > 3:
            # Keras 1 stores the running *variance* under the name running_std
            state["var"] = jnp.asarray(ws[3], jnp.float32)
    else:
        raise InvalidKerasConfigurationException(
            f"no weight mapping for layer class {cls}")


# ---------------------------------------------------------------------------
# Public API (reference KerasModelImport facade)
# ---------------------------------------------------------------------------

class KerasModelImport:
    """Static facade, mirroring reference KerasModelImport.java:60."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str, *, enforce_training_config: bool = False):
        """Keras Sequential HDF5 archive -> initialized MultiLayerNetwork with
        copied weights (reference importSequentialModelAndWeights:110)."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with H5File(path) as f:
            model_config = json.loads(f.read_attr("/", "model_config"))
            if model_config.get("class_name") != "Sequential":
                raise InvalidKerasConfigurationException(
                    "not a Sequential model; use import_keras_model_and_weights")
            loss = _training_loss(f, enforce_training_config)
            parse = _parse_sequential(model_config, loss)
            conf = _build_mln_conf(parse)
            net = MultiLayerNetwork(conf).init()
            root = _weight_root(f)
            klayers = _keras_layers(model_config)
            for kl in klayers:
                cfg = kl.get("config", {})
                name = cfg.get("name")
                if name not in parse.index_of:
                    continue
                idx = parse.index_of[name]
                wnames, ws = _layer_weights(f, root, name)
                _set_layer_params(kl["class_name"], cfg, net.params_list[idx],
                                  net.state_list[idx], ws, wnames)
        return net

    @staticmethod
    def import_keras_model_and_weights(path: str):
        """Keras functional-API HDF5 archive -> initialized ComputationGraph
        (reference importModelAndWeights:85). Merge -> MergeVertex (concat) or
        ElementWiseVertex (sum/mul)."""
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph

        with H5File(path) as f:
            model_config = json.loads(f.read_attr("/", "model_config"))
            if model_config.get("class_name") == "Sequential":
                raise InvalidKerasConfigurationException(
                    "Sequential model; use "
                    "import_keras_sequential_model_and_weights")
            loss = _training_loss(f, False)
            conf, class_of, cfg_of = _build_graph_conf(model_config, loss)
            net = ComputationGraph(conf).init()
            root = _weight_root(f)
            for name, cls in class_of.items():
                if name not in net.params_list:
                    continue
                wnames, ws = _layer_weights(f, root, name)
                _set_layer_params(cls, cfg_of[name], net.params_list[name],
                                  net.state_list.get(name, {}), ws, wnames)
        return net

    @staticmethod
    def import_keras_model_configuration(path_or_json: str):
        """Model-config JSON (file path or raw string) -> configuration only
        (reference importKerasModelConfiguration)."""
        s = path_or_json
        if not s.lstrip().startswith("{"):
            with open(s) as fh:
                s = fh.read()
        model_config = json.loads(s)
        if model_config.get("class_name") == "Sequential":
            return _build_mln_conf(_parse_sequential(model_config, None))
        return _build_graph_conf(model_config, None)[0]


def _training_loss(f: H5File, enforce: bool) -> Optional[str]:
    if not f.has_attr("/", "training_config"):
        if enforce:
            raise InvalidKerasConfigurationException(
                "model has no training_config (was it compiled before "
                "saving?)")
        return None
    tc = json.loads(f.read_attr("/", "training_config"))
    kloss = tc.get("loss")
    if isinstance(kloss, dict):
        kloss = next(iter(kloss.values()), None)
    if kloss is None:
        return None
    if kloss not in _LOSSES:
        raise InvalidKerasConfigurationException(
            f"unsupported Keras loss {kloss!r}")
    return _LOSSES[kloss]


def _build_mln_conf(parse: _SequentialParse):
    lb = NeuralNetConfiguration.builder().list()
    for lyr in parse.layers:
        lb.layer(lyr)
    if parse.input_type is not None:
        lb.set_input_type(parse.input_type)
    return lb.build()


def _build_graph_conf(model_config: dict, loss: Optional[str]):
    cfg = model_config["config"]
    klayers = cfg["layers"]
    output_names = {ol[0] for ol in cfg["output_layers"]}
    gb = NeuralNetConfiguration.builder().graph_builder()
    input_types = []
    class_of: Dict[str, str] = {}
    cfg_of: Dict[str, dict] = {}
    for kl in klayers:
        cls = kl["class_name"]
        lcfg = kl.get("config", {})
        name = lcfg.get("name")
        class_of[name] = cls
        cfg_of[name] = lcfg
        inbound = [n[0] for node in kl.get("inbound_nodes", []) for n in node]
        if cls == "InputLayer":
            gb.add_inputs(name)
            input_types.append(_input_type_from_shape(
                lcfg["batch_input_shape"][1:],
                lcfg.get("dim_ordering", "tf")))
            continue
        if cls == "Merge":
            mode = lcfg.get("mode", "concat")
            if mode == "concat":
                gb.add_vertex(name, MergeVertex(), *inbound)
            elif mode in ("sum", "ave", "mul", "max"):
                op = {"sum": "add", "ave": "average", "mul": "product",
                      "max": "max"}[mode]
                gb.add_vertex(name, ElementWiseVertex(op=op), *inbound)
            else:
                raise InvalidKerasConfigurationException(
                    f"unsupported Merge mode {mode!r}")
            continue
        lyr = _to_layer(cls, lcfg, last=name in output_names, loss=loss)
        if lyr is None:
            # shape-only (Flatten/Reshape): collapse onto the inbound name
            class_of.pop(name)
            # map consumers of this name to its input
            for other in klayers:
                for node in other.get("inbound_nodes", []):
                    for n in node:
                        if n[0] == name:
                            n[0] = inbound[0]
            continue
        gb.add_layer(name, lyr, *inbound)
    gb.set_outputs(*[ol[0] for ol in cfg["output_layers"]])
    if input_types:
        gb.set_input_types(*input_types)
    return gb.build(), class_of, cfg_of
