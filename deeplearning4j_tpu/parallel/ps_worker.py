"""Out-of-process parameter-server worker.

Run as::

    python -m deeplearning4j_tpu.parallel.ps_worker \
        --addr 127.0.0.1:<port> --conf conf.json --data worker0.npz \
        --worker-id 0 --push-frequency 4 --codec bf16 --delay 0.0

Spawned by ``ParameterServerParallelWrapper`` (transport="tcp") and by the
multi-process tests — the same separate-OS-process pattern as
tests/_dist_worker.py, but joined through the PS TCP protocol instead of
jax.distributed: each worker owns its interpreter and device, pulls the
initial params from the server, trains its batch shard asynchronously
(pushing staleness-weighted deltas), and prints ONE JSON stats line on
stdout for the parent to parse.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True, help="host:port of the PS")
    ap.add_argument("--conf", required=True, help="model config JSON path")
    ap.add_argument("--data", required=True,
                    help=".npz with x (n,B,...) / y (n,B,...) batch stacks")
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--push-frequency", type=int, default=4)
    ap.add_argument("--codec", default="none", choices=("none", "bf16"))
    ap.add_argument("--delay", type=float, default=0.0,
                    help="straggler fault injection: sleep per step")
    args = ap.parse_args(argv)

    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.conf.serde import from_json
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.param_server import (
        make_compiled_worker_step, run_worker_loop)
    from deeplearning4j_tpu.parallel.ps_transport import TcpTransport

    with open(args.conf) as f:
        conf = from_json(f.read())
    net = MultiLayerNetwork(conf).init()  # shapes only; params come from PS

    blob = np.load(args.data)
    batches = [DataSet(x, y) for x, y in zip(blob["x"], blob["y"])]
    it = iter(batches)

    host, port = args.addr.rsplit(":", 1)
    transport = TcpTransport((host, int(port)), codec=args.codec)
    step = make_compiled_worker_step(net, transport="tcp")
    try:
        stats = run_worker_loop(
            transport=transport, replica=net,
            step_fn=(step.fn if step is not None else None),
            next_batch=lambda: next(it, None),
            push_frequency=args.push_frequency,
            delay_s=args.delay, worker_id=args.worker_id)
    finally:
        transport.close()
    # stdout carries exactly one JSON line: the parent's parse contract
    print(json.dumps(stats), flush=True)  # lint: bare-print-ok (subprocess stdout protocol, not logging)


if __name__ == "__main__":
    main(sys.argv[1:])
