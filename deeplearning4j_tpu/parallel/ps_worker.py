"""Out-of-process parameter-server worker.

Static-shard mode (spawned by ``ParameterServerParallelWrapper``,
transport="tcp"/"shm") trains a pre-materialized batch stack — an .npz
path, or ``shm://<segment>`` when the coordinator shipped the shard
through a shared-memory segment (``--ps-transport shm`` additionally moves
the push/pull tensor bytes into shm rings)::

    python -m deeplearning4j_tpu.parallel.ps_worker \
        --addr 127.0.0.1:<port> --conf conf.json --data worker0.npz \
        --worker-id 0 --push-frequency 4 --codec bf16 --delay 0.0

Elastic mode (spawned by ``parallel.elastic.ElasticTrainer``) registers
with the membership oracle, heartbeats its lease, and consumes its shard
from a broker topic under a committed-offset consumer group::

    python -m deeplearning4j_tpu.parallel.ps_worker \
        --addr 127.0.0.1:<ps_port> --conf conf.json \
        --broker 127.0.0.1:<broker_port> --topic shard-0 --group shard-0 \
        --shard 0 --worker-name shard0-gen0

Either way each worker owns its interpreter and device, pulls the initial
params from the server, trains asynchronously (pushing staleness-weighted
deltas), and prints ONE JSON stats line on stdout for the parent to parse.
On exit — clean, fenced, or crashed — the shard .npz (if any) is removed
(atexit + finally) and a ``worker_exit`` flight-recorder event carries the
exit reason.
"""
from __future__ import annotations

import argparse
import atexit
import json
import os
import sys


def _parse_addr(addr: str):
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def _run_npz(args, net, step, transport):
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel.param_server import run_worker_loop

    if args.data.startswith("shm://"):
        from deeplearning4j_tpu.parallel.ps_transport import (
            read_shard_segment)
        blob = read_shard_segment(args.data[len("shm://"):])
    else:
        blob = np.load(args.data)
    batches = [DataSet(x, y) for x, y in zip(blob["x"], blob["y"])]
    it = iter(batches)
    return run_worker_loop(
        transport=transport, replica=net,
        step_fn=(step.fn if step is not None else None),
        next_batch=lambda: next(it, None),
        push_frequency=args.push_frequency,
        delay_s=args.delay, worker_id=args.worker_id)


def _run_elastic(args, net, step, transport):
    """Membership-leased, broker-fed worker: register -> heartbeat ->
    consume shard topic -> commit offsets only at push-window boundaries
    (so a crash redelivers at most one window to the replacement)."""
    import queue
    import threading

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.observability.federation import MetricsPublisher
    from deeplearning4j_tpu.parallel.param_server import (
        StaleEpochFenced, run_worker_loop)
    from deeplearning4j_tpu.parallel.ps_transport import TransportError
    from deeplearning4j_tpu.streaming.broker import ReconnectingConsumer

    reg = transport.register(args.shard, worker=args.worker_name)
    member, epoch = reg["member"], reg["epoch"]
    lease_s = float(reg["lease_s"])
    transport.bind_member(member, epoch)

    stop = threading.Event()
    stop_reason = ["done"]
    hb = transport.clone()
    # federation: ship cumulative metric snapshots + flight events + finished
    # traces on a cloned channel; the final flush after the run loop is what
    # makes the coordinator's fleet totals exact
    pub_transport = transport.clone()
    publisher = MetricsPublisher(
        pub_transport, name=args.worker_name or f"worker-{args.worker_id}",
        role="worker")

    def _heartbeats() -> None:
        # renew at a third of the lease so two misses still leave slack;
        # a False renewal means the oracle already declared us dead — stop
        # consuming immediately, the flush will be fenced anyway
        interval = max(0.05, lease_s / 3.0)
        while not stop.wait(interval):
            try:
                if not hb.heartbeat():
                    stop_reason[0] = "lease-expired"
                    stop.set()
                    return
            except TransportError:
                stop_reason[0] = "coordinator-unreachable"
                stop.set()
                return

    threading.Thread(target=_heartbeats, daemon=True,
                     name="ps-heartbeat").start()
    publisher.start()

    consumer = ReconnectingConsumer(
        _parse_addr(args.broker), args.topic, group=args.group)
    saw_fin = [False]

    def next_batch():
        while not stop.is_set():
            try:
                meta, arrays = consumer.get(timeout=0.5)
            except queue.Empty:
                continue
            if meta.get("fin"):
                saw_fin[0] = True
                return None
            # parent subsequent pushes under the consume span of the batch
            # being trained: producer -> consume -> push stitch into one trace
            transport.bind_trace_parent(consumer.last_trace_ref)
            return DataSet(arrays["x"], arrays["y"])
        return None

    def on_push(accepted: bool) -> None:
        # the window's delta landed on the PS: NOW its samples count as
        # consumed (commit-after-push = at-least-once, duplicates bounded
        # by one window)
        if accepted:
            consumer.commit_delivered()

    try:
        stats = run_worker_loop(
            transport=transport, replica=net,
            step_fn=(step.fn if step is not None else None),
            next_batch=next_batch, push_frequency=args.push_frequency,
            delay_s=args.delay, worker_id=member, on_push=on_push)
        if saw_fin[0] and not stop.is_set():
            # the fin marker is the shard-complete record: committing it
            # tells the coordinator no replacement is needed
            consumer.commit_delivered()
    finally:
        stop.set()
        consumer.close()
        # the final cumulative frame must land before deregister/close —
        # it carries the last push-window's counters, and exact fleet
        # totals depend on it (final frames bypass fencing server-side)
        publisher.stop(final=True)
        pub_transport.close()
        hb.close()
    if stop_reason[0] == "lease-expired":
        raise StaleEpochFenced("membership lease expired mid-shard")
    if stop_reason[0] == "coordinator-unreachable":
        raise TransportError("heartbeat channel lost")
    try:
        transport.deregister("done")
    except TransportError:  # lint: swallowed-exception-ok (lease will lapse server-side; work is already committed)
        pass
    stats.update(member=member, epoch=epoch, shard=args.shard,
                 fin=saw_fin[0])
    return stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True, help="host:port of the PS")
    ap.add_argument("--conf", required=True, help="model config JSON path")
    ap.add_argument("--data",
                    help=".npz with x (n,B,...) / y (n,B,...) batch stacks")
    ap.add_argument("--broker", help="host:port of the shard broker "
                                     "(elastic mode)")
    ap.add_argument("--topic", help="shard topic to consume (elastic mode)")
    ap.add_argument("--group", help="consumer group id; the replacement "
                                    "resumes this group's committed offset")
    ap.add_argument("--shard", type=int, default=0)
    ap.add_argument("--worker-name", default="",
                    help="coordinator-chosen name; lets the parent map this "
                         "process to its membership lease")
    ap.add_argument("--worker-id", type=int, default=0)
    ap.add_argument("--push-frequency", type=int, default=4)
    ap.add_argument("--codec", default="none", choices=("none", "bf16"))
    ap.add_argument("--ps-transport", default="tcp",
                    choices=("tcp", "shm"),
                    help="shm = tensor bytes through shared-memory rings "
                         "(negotiated; degrades to tcp frames if segments "
                         "can't attach)")
    ap.add_argument("--delay", type=float, default=0.0,
                    help="straggler fault injection: sleep per step")
    args = ap.parse_args(argv)
    if bool(args.broker) == bool(args.data):
        ap.error("exactly one of --data (static shard) or "
                 "--broker/--topic/--group (elastic) is required")
    if args.broker and not (args.topic and args.group):
        ap.error("--broker requires --topic and --group")

    from deeplearning4j_tpu.nn.conf.serde import from_json
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.observability.flight_recorder import (
        global_recorder as _flight_recorder,
    )
    from deeplearning4j_tpu.parallel.param_server import (
        StaleEpochFenced, make_compiled_worker_step)
    from deeplearning4j_tpu.parallel.ps_transport import (
        ShmTransport, TcpTransport, TransportError)

    def _cleanup_data() -> None:
        # the shard file is this worker's to delete: the parent only wrote
        # it for us, and a preempted pod's scratch must not accumulate
        # (shm:// shards are the COORDINATOR's segments — it unlinks them)
        if args.data and not args.data.startswith("shm://"):
            try:
                os.unlink(args.data)
            except OSError:  # lint: swallowed-exception-ok (already removed, or parent tmpdir gone first)
                pass

    atexit.register(_cleanup_data)

    with open(args.conf) as f:
        conf = from_json(f.read())
    net = MultiLayerNetwork(conf).init()  # shapes only; params come from PS

    cls = ShmTransport if args.ps_transport == "shm" else TcpTransport
    transport = cls(_parse_addr(args.addr), codec=args.codec)
    step = make_compiled_worker_step(net, transport="tcp")
    reason, rc, stats = "done", 0, None
    try:
        if args.broker:
            stats = _run_elastic(args, net, step, transport)
        else:
            stats = _run_npz(args, net, step, transport)
    except StaleEpochFenced as e:
        reason, rc = "fenced", 3
        sys.stderr.write(f"{e}\n")
    except TransportError as e:
        reason, rc = "coordinator-unreachable", 4
        sys.stderr.write(f"{e}\n")
    except BaseException as e:
        reason = f"error:{type(e).__name__}"
        raise
    finally:
        _flight_recorder().record(
            "worker_exit", worker=args.worker_name or str(args.worker_id),
            shard=args.shard, reason=reason)
        _cleanup_data()
        transport.close()
    if stats is not None:
        stats["exit_reason"] = reason
        # stdout carries exactly one JSON line: the parent's parse contract
        print(json.dumps(stats), flush=True)  # lint: bare-print-ok (subprocess stdout protocol, not logging)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
