"""Cluster-style distributed training: TrainingMaster SPI + parameter averaging.

Reference: deeplearning4j-scaleout dl4j-spark — api/TrainingMaster.java +
api/TrainingWorker.java SPIs; impl/paramavg/ParameterAveragingTrainingMaster.java
(executeTraining:344 splits the RDD into averaging intervals, repartitions:654,
runs ExecuteWorkerFlatMap per partition:659, tree-aggregates parameters:772 and
sets the average on the master:782); front-ends impl/multilayer/
SparkDl4jMultiLayer.java and impl/graph/SparkComputationGraph.java; per-phase
timing stats in spark/stats/ with HTML timeline export (StatsUtils.java).

TPU-native redesign: Spark executors + tree-aggregate become a device mesh —
each "worker" is a mesh slot running the jitted local train step via shard_map
over stacked per-replica parameters, and the parameter average is a mean over
the replica axis (one XLA reduction over ICI/DCN instead of a driver round
trip). The TrainingMaster/TrainingWorker SPI and the stats surface survive.
"""
from __future__ import annotations

import functools
import json
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu import common

from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.observability.names import COLLECTIVE_BYTES_TOTAL
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry, tree_nbytes as _tree_nbytes,
)
from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
from deeplearning4j_tpu.parallel.compile_seam import compile_step
from deeplearning4j_tpu.parallel.partition import (
    pspec as P, named_sharding as _named_sharding,
)


class TrainingMaster:
    """SPI (reference api/TrainingMaster.java)."""

    def execute_training(self, model, data_iterator) -> None:
        raise NotImplementedError

    def get_training_stats(self):
        return None


class TrainingWorker:
    """SPI (reference api/TrainingWorker.java) — processes minibatches locally
    and emits a result for aggregation."""

    def get_initial_model(self):
        raise NotImplementedError

    def process_minibatch(self, dataset, model):
        raise NotImplementedError

    def get_final_result(self, model):
        raise NotImplementedError


class SparkTrainingStats:
    """Per-phase timing collection (reference stats/CommonSparkTrainingStats.java).
    Event = (phase, start_ms, duration_ms, meta)."""

    def __init__(self):
        self.events: List[dict] = []

    def add(self, phase: str, start: float, duration: float, **meta) -> None:
        self.events.append({"phase": phase, "start_ms": int(start * 1000),
                            "duration_ms": duration * 1000, **meta})

    def phases(self) -> List[str]:
        return sorted({e["phase"] for e in self.events})

    def total_time_ms(self, phase: str) -> float:
        return sum(e["duration_ms"] for e in self.events if e["phase"] == phase)

    def export_html(self, path: str) -> None:
        """Self-contained SVG timeline (reference StatsUtils.exportStatsAsHTML)."""
        if not self.events:
            open(path, "w").write("<html><body>No events</body></html>")
            return
        t0 = min(e["start_ms"] for e in self.events)
        t1 = max(e["start_ms"] + e["duration_ms"] for e in self.events)
        span = max(t1 - t0, 1.0)
        phases = self.phases()
        colors = ["#4C78A8", "#F58518", "#54A24B", "#E45756", "#72B7B2",
                  "#B279A2"]
        width, row_h = 960, 28
        rows = []
        for e in self.events:
            row = phases.index(e["phase"])
            x = 80 + (e["start_ms"] - t0) / span * (width - 100)
            w = max(e["duration_ms"] / span * (width - 100), 1.0)
            c = colors[row % len(colors)]
            rows.append(f'<rect x="{x:.1f}" y="{row*row_h+6}" width="{w:.1f}" '
                        f'height="{row_h-10}" fill="{c}"><title>{e["phase"]}: '
                        f'{e["duration_ms"]:.1f} ms</title></rect>')
        labels = [f'<text x="4" y="{i*row_h+row_h//2+4}" font-size="11">{p}</text>'
                  for i, p in enumerate(phases)]
        html = (f'<html><body><h3>Training timeline</h3>'
                f'<svg width="{width}" height="{len(phases)*row_h+20}" '
                f'font-family="sans-serif">{"".join(labels)}{"".join(rows)}'
                f'</svg><pre>{json.dumps(self.summary(), indent=2)}</pre>'
                f'</body></html>')
        open(path, "w").write(html)

    def summary(self) -> dict:
        return {p: {"count": sum(1 for e in self.events if e["phase"] == p),
                    "total_ms": round(self.total_time_ms(p), 2)}
                for p in self.phases()}


class ParameterAveragingTrainingMaster(TrainingMaster):
    """BSP parameter averaging over the device mesh
    (reference impl/paramavg/ParameterAveragingTrainingMaster.java)."""

    def __init__(self, num_workers: Optional[int] = None,
                 batch_size_per_worker: int = 32,
                 averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 collect_training_stats: bool = False,
                 mesh: Optional[Mesh] = None,
                 prefetch: int = 2):
        self.mesh = mesh or data_parallel_mesh(num_workers)
        self.num_workers = self.mesh.shape["data"]
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.collect_training_stats = collect_training_stats
        self.stats = SparkTrainingStats() if collect_training_stats else None
        #: splits staged + transferred ahead of the shard_map dispatch loop
        #: (see MultiLayerNetwork.prefetch_depth); 0 = synchronous staging
        self.prefetch = prefetch
        self._local_fns = {}

    class Builder:
        def __init__(self, num_workers: Optional[int] = None):
            self._kw = {"num_workers": num_workers}

        def batch_size_per_worker(self, n: int):
            self._kw["batch_size_per_worker"] = n
            return self

        def averaging_frequency(self, n: int):
            self._kw["averaging_frequency"] = n
            return self

        def average_updaters(self, flag: bool):
            self._kw["average_updaters"] = flag
            return self

        def collect_training_stats(self, flag: bool):
            self._kw["collect_training_stats"] = flag
            return self

        def mesh(self, mesh: Mesh):
            self._kw["mesh"] = mesh
            return self

        def prefetch(self, n: int):
            self._kw["prefetch"] = n
            return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(**self._kw)

    # ------------------------------------------------------------------ internals
    def _fns_for(self, model):
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, make_graph_train_step
        from deeplearning4j_tpu.nn.multilayer import make_train_step

        # keyed on the effective dtype policy: a conf-declared dtype pins the
        # program (make_*_train_step wraps it), so only unpinned programs are
        # re-keyed when the global policy changes
        key = (id(model.conf),) + common.effective_policy_key(
            getattr(model.conf.global_conf, "dtype", None))
        if key in self._local_fns:
            return self._local_fns[key]
        # switching the global dtype policy must not grow the cache without
        # bound: drop programs traced under a policy that no longer applies
        for stale in [k for k in self._local_fns if k[0] == key[0]
                      and k[1:] != key[1:]]:
            del self._local_fns[stale]
        mesh = self.mesh
        if isinstance(model, ComputationGraph):
            graph_base = make_graph_train_step(model.conf)
            base = lambda p, s, u, x, y, r, it: graph_base(p, s, u, [x], [y], r, it)
        else:
            base = make_train_step(model.conf)
        stacked, repl = P("data"), P()

        def local_steps(params, states, upd, xs, ys, rng, it0):
            # xs: (1, F, B, ...) this replica's F sequential minibatches
            sq = functools.partial(jax.tree_util.tree_map, lambda a: a[0])
            ex = functools.partial(jax.tree_util.tree_map, lambda a: a[None])
            p, s, u = sq(params), sq(states), sq(upd)
            xs, ys = xs[0], ys[0]
            rng_local = jax.random.fold_in(rng, jax.lax.axis_index("data"))

            def body(carry, xy):
                p, s, u, it = carry
                x, y = xy
                p, s, u, loss = base(p, s, u, x, y,
                                     jax.random.fold_in(rng_local, it), it)
                return (p, s, u, it + 1), loss

            (p, s, u, _), losses = jax.lax.scan(body, (p, s, u, it0), (xs, ys))
            return ex(p), ex(s), ex(u), jax.lax.pmean(jnp.mean(losses), "data")

        # compiled through the seam with check_vma=False so flash/LSTM
        # pallas kernels engage inside the per-replica body (a checked
        # shard_map downgrades them to XLA math); outputs are replicated by
        # the body's own pmean, so unchecked is safe
        local = compile_step(
            "TrainingMaster.local_steps", local_steps, mesh=mesh,
            rule_set="dp",
            in_specs=(stacked, stacked, stacked, stacked, stacked, repl, repl),
            out_specs=(stacked, stacked, stacked, repl),
            strategy="shard_map", check_vma=False, cache_key=key,
            conf=model.conf)

        def average(params, states, upd):
            mean_b = lambda a: jnp.broadcast_to(
                jnp.mean(a, axis=0, keepdims=True), a.shape)
            params = jax.tree_util.tree_map(mean_b, params)
            states = jax.tree_util.tree_map(mean_b, states)
            if self.average_updaters:
                upd = jax.tree_util.tree_map(mean_b, upd)
            return params, states, upd

        fns = (local,
               compile_step("TrainingMaster.average", average, mesh=mesh,
                            rule_set="dp", strategy="jit", cache_key=key,
                            conf=model.conf))
        self._local_fns[key] = fns
        return fns

    # ------------------------------------------------------------------ training
    @_dump_on_unhandled("TrainingMaster.execute_training")
    def execute_training(self, model, data_iterator) -> None:
        """One pass over the iterator (reference executeTraining:344). Minibatches
        are grouped into splits of num_workers*averaging_frequency; each worker
        runs its averaging_frequency batches sequentially inside one jitted
        shard_map call, then parameters (+ updater state) are averaged."""
        D, F = self.num_workers, self.averaging_frequency
        local, average = self._fns_for(model)
        sharding = _named_sharding(self.mesh, P("data"))
        stack = functools.partial(
            jax.tree_util.tree_map,
            lambda a: jax.device_put(
                jnp.broadcast_to(a[None], (D,) + a.shape), sharding))

        t_setup = time.time()
        params = stack(model.params_list)
        states = stack(model.state_list)
        upd = stack(model.updater_state)
        if self.stats:
            self.stats.add("BroadcastParameters", t_setup, time.time() - t_setup)

        split: List = []
        if hasattr(data_iterator, "reset"):
            data_iterator.reset()
        # each averaging round psum-means ~per-replica param bytes
        avg_bytes = _obs_registry().counter(
            COLLECTIVE_BYTES_TOTAL,
            "bytes moved by host-dispatched collectives, by op and site"
        ).labels(op="parameter_average", site="training_master")
        param_bytes = _tree_nbytes(model.params_list)

        def splits():
            rows: List[List] = [[] for _ in range(D)]
            filled = 0
            for ds in data_iterator:
                rows[filled % D].append(ds)
                filled += 1
                if filled == D * F:
                    yield rows
                    rows = [[] for _ in range(D)]
                    filled = 0
            if filled and filled % D == 0:
                # partial split: fewer sequential steps, same worker count
                yield rows
            # else: drop the ragged tail (reference repartitions to avoid
            # this; batch counts not divisible by the worker count skipped)

        def stage(split_batches):
            # producer thread: the next split's (D, F, B, ...) stacks are
            # built and put in flight (non-blocking sharded device_put)
            # while the current split's shard_map local steps execute
            t0 = time.time()
            # lint: host-sync-in-hot-loop-ok (producer-thread host stacking of iterator output)
            xs = np.stack([np.stack([np.asarray(ds.features) for ds in row])
                           for row in split_batches])
            # lint: host-sync-in-hot-loop-ok (producer-thread host stacking of iterator output)
            ys = np.stack([np.stack([np.asarray(ds.labels) for ds in row])
                           for row in split_batches])
            xs = jax.device_put(xs, sharding)
            ys = jax.device_put(ys, sharding)
            if self.stats:
                self.stats.add("SplitData", t0, time.time() - t0)
            return xs, ys

        def run_split(xs, ys):
            nonlocal params, states, upd
            f = int(xs.shape[1])  # F, or fewer on a partial split
            t1 = time.time()
            params, states, upd, loss = local(
                params, states, upd, xs, ys, model._next_rng(),
                jnp.int32(model.iteration))
            model.iteration += f
            if self.stats:
                # stats want the realized loss; this is the only host sync
                # in the split and only happens when stats are collected
                self.stats.add("WorkerFit", t1, time.time() - t1,
                               loss=float(loss))  # lint: host-sync-in-hot-loop-ok (stats-only sync, gated on self.stats)
            _compile_tracker().note_step(f, fn="TrainingMaster.local_steps")
            _flight_recorder().record(
                "step", path="TrainingMaster.local_steps",
                it=model.iteration, k=f, dispatch_s=time.time() - t1)
            t2 = time.time()
            params, states, upd = average(params, states, upd)
            avg_bytes.inc(param_bytes)
            _flight_recorder().record(
                "step", path="TrainingMaster.average", it=model.iteration,
                collective_bytes=param_bytes, dispatch_s=time.time() - t2)
            if self.stats:
                self.stats.add("AverageParameters", t2, time.time() - t2)
            model.score_value = loss
            for listener in model.listeners:
                listener.iteration_done(model, model.iteration)
            _wd_beat(model.iteration)

        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher

        pf = DevicePrefetcher(splits(), stage, depth=self.prefetch,
                              path="training_master")
        for xs, ys in pf:
            run_split(xs, ys)

        t3 = time.time()
        # lint: host-sync-in-hot-loop-ok (final param pull-back after the fit loop ends)
        unstack = functools.partial(jax.tree_util.tree_map, lambda a: np.asarray(a[0]))
        model.params_list = jax.tree_util.tree_map(jnp.asarray, unstack(params))
        model.state_list = jax.tree_util.tree_map(jnp.asarray, unstack(states))
        model.updater_state = jax.tree_util.tree_map(jnp.asarray, unstack(upd))
        if self.stats:
            self.stats.add("SetParametersOnMaster", t3, time.time() - t3)

    def get_training_stats(self) -> Optional[SparkTrainingStats]:
        return self.stats


class DistributedMultiLayer:
    """Front-end (reference impl/multilayer/SparkDl4jMultiLayer.java)."""

    def __init__(self, model, training_master: TrainingMaster):
        self.model = model
        self.master = training_master
        # jitted sharded forward, built on first use and rebuilt on dtype-
        # policy change (the policy is read at trace time)
        self._eval_fwd = None
        self._eval_fwd_policy = None

    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            self.master.execute_training(self.model, iter(data)
                                         if isinstance(data, list) else data)
        return self.model

    def evaluate(self, iterator):
        """Distributed evaluation (reference impl/multilayer/evaluation/):
        forward passes run data-sharded over the master's mesh; the confusion
        matrix accumulates on host and merges across batches."""
        mesh = getattr(self.master, "mesh", None)
        if mesh is None or "data" not in mesh.shape:
            return self.model.evaluate(iterator)
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        n = mesh.shape["data"]
        net = self.model
        conf_dtype = getattr(net.conf.global_conf, "dtype", None)
        eff = common.effective_policy_key(conf_dtype)
        if self._eval_fwd is None or self._eval_fwd_policy != eff:
            self._eval_fwd_policy = eff
            if isinstance(net, MultiLayerNetwork):
                fwd_py = lambda p, s, x: net._output_pure(p, s, x, train=False)[0]
            else:
                fwd_py = lambda p, s, x: net._output_pure(p, s, [x])[0][0]
            # a conf-declared dtype pins this program like LazyScore._jit
            # does; the seam adds CompileTracker attribution the old ad-hoc
            # jit lacked
            self._eval_fwd = compile_step(
                "DistributedMultiLayer.eval_fwd",
                common.wrap_with_policy(fwd_py, conf_dtype), mesh=mesh,
                rule_set="dp", in_specs=(P(), P(), P("data")),
                strategy="jit", cache_key=eff,
                conf=getattr(net, "conf", None))
        fwd = self._eval_fwd
        params, states = net.params_list, net.state_list
        e = Evaluation()
        for ds in iterator:
            x, y = np.asarray(ds.features), np.asarray(ds.labels)
            pad = (-len(x)) % n
            if pad:  # batch must divide the data axis; pad and trim below
                x = np.concatenate([x, np.repeat(x[-1:], pad, 0)])
            out = np.asarray(fwd(params, states, jnp.asarray(x)))
            if pad:
                out = out[:-pad]
            # out is trimmed back to len(y), so the original mask aligns
            e.eval(y, out, mask=ds.labels_mask)
        return e

    def get_score(self) -> float:
        return self.model.score_value


# The graph front-end shares the implementation (the master dispatches on the
# model type); alias mirrors the reference naming.
DistributedComputationGraph = DistributedMultiLayer
