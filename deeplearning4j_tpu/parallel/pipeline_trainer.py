"""PipelineTrainer: train a plain network config through the GPipe executor.

Round-4 verdict: parallel/pipeline.py was exact + differentiable but
standalone — no network config could train through it. This closes the gap
the way reference ParallelWrapper.java:44 wraps any net: hand a
``MultiLayerNetwork`` (e.g. models.transformer_lm) to PipelineTrainer and
``fit()`` runs the homogeneous middle of the stack — automatically detected
as the longest run of identical layer configs — as pipeline stages over the
mesh's ``stage`` axis, while the surrounding layers (embedding, output/loss)
run replicated. Gradients flow through the pipeline's ppermutes by autodiff
(the reverse pipeline), and parameter updates reuse the standard
make_train_step updater/clipping/schedule semantics, so pipelined training
is step-for-step equivalent to single-device training on the same batches
(tests/test_pipeline_trainer.py pins it).
"""
from __future__ import annotations

import time as _time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.parallel.mesh import build_mesh
from deeplearning4j_tpu.parallel.pipeline import PipelineParallel
from deeplearning4j_tpu.parallel.wrapper import (
    _t_staging, _t_dispatch, _t_listeners,
)


def find_block_run(layers) -> tuple:
    """Longest run of consecutive, identical (dataclass-equal) layer configs
    — the pipeline-able stack. The final (loss) layer never joins it."""
    best = (0, 0)
    i = 0
    n = len(layers) - 1  # exclude the loss layer
    while i < n:
        j = i + 1
        while j < n and layers[j] == layers[i]:
            j += 1
        if j - i > best[1] - best[0]:
            best = (i, j)
        i = j
    return best


class PipelineTrainer:
    """GPipe training for configs with a homogeneous block stack.

    ``n_microbatches`` trades bubble fraction (S-1)/(S+M-1) for per-tick
    activation size. Blocks must be stateless and dropout-free (the pipeline
    body threads no per-block state/rng); everything else about the config —
    updaters, schedules, clipping, regularization, aux losses of the non-
    pipelined layers — behaves exactly as in single-device fit().
    """

    def __init__(self, net, mesh: Optional[Mesh] = None,
                 n_stages: Optional[int] = None, axis_name: str = "stage",
                 n_microbatches: int = 4):
        self.net = net
        conf = net.conf
        self.mesh = mesh or build_mesh(
            {axis_name: n_stages or len(jax.devices())})
        self.axis_name = axis_name
        self.n_stages = self.mesh.shape[axis_name]
        i0, i1 = find_block_run(conf.layers)
        if i1 - i0 < 2:
            raise ValueError("config has no homogeneous block stack to "
                             "pipeline (need >= 2 identical consecutive "
                             "layer configs)")
        if (i1 - i0) % self.n_stages:
            raise ValueError(f"{i1 - i0} pipeline blocks not divisible by "
                             f"{self.n_stages} stages")
        block = conf.layers[i0]
        if getattr(block, "dropout", None):
            raise ValueError("pipelined blocks must be dropout-free")
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        if block.init_state(InputType.recurrent(block.n_out or 1, 1)):
            # e.g. MoETransformerBlock: its aux_loss state would be silently
            # dropped by the stateless pipeline body — training would lose
            # the Switch load-balance term with no error
            raise ValueError("pipelined blocks must be stateless "
                             f"({type(block).__name__} publishes state)")
        for i in range(i0, i1):
            if conf.preprocessor(i) is not None:
                raise ValueError("preprocessors inside the pipelined block "
                                 "run are not supported")
        self.block_range = (i0, i1)
        self._block = block
        block_fn = lambda p, x: block.apply(p, {}, x, train=True, rng=None)[0]
        if conf.global_conf.gradient_checkpointing:
            # same remat contract as multilayer.loss_fn: backward recomputes
            # each block's forward instead of holding its activations
            block_fn = jax.checkpoint(block_fn)
        self.pipe = PipelineParallel(
            self.mesh, block_fn, n_blocks=i1 - i0, axis_name=axis_name,
            n_microbatches=n_microbatches)
        self._step = None

    # ------------------------------------------------------------------ loss
    def _pipeline_loss(self, params_list, state_list, x, y, rng, fmask=None,
                       lmask=None):
        """multilayer.loss_fn with the block run executed as a pipeline.
        Same return contract: (loss, new_state_list)."""
        from deeplearning4j_tpu.nn.multilayer import (
            _aux_losses, _regularization)

        conf = self.net.conf
        layers = conf.layers
        i0, i1 = self.block_range
        last = layers[-1]
        remat = conf.global_conf.gradient_checkpointing
        rngs = (jax.random.split(rng, len(layers))
                if rng is not None else [None] * len(layers))

        def apply_one(i, h):
            # same remat contract as multilayer.loss_fn for the layers
            # outside the pipelined run
            pp = conf.preprocessor(i)
            if pp is not None:
                h = pp.pre_process(h, fmask)
            if remat:
                def f(p, hh, _l=layers[i], _s=state_list[i], _r=rngs[i]):
                    return _l.apply(p, _s, hh, train=True, rng=_r, mask=fmask)
                return jax.checkpoint(f)(params_list[i], h)
            return layers[i].apply(params_list[i], state_list[i], h,
                                   train=True, rng=rngs[i], mask=fmask)

        h = x
        new_states = []
        for i in range(i0):
            h, ns = apply_one(i, h)
            new_states.append(ns)
        stacked = {k: jnp.stack([params_list[i][k] for i in range(i0, i1)])
                   for k in params_list[i0]}
        h = self.pipe(stacked, h)
        new_states.extend(state_list[i0:i1])
        for i in range(i1, len(layers) - 1):
            h, ns = apply_one(i, h)
            new_states.append(ns)
        pp = conf.preprocessor(len(layers) - 1)
        if pp is not None:
            h = pp.pre_process(h, fmask)
        h = last.apply_dropout(h, rngs[-1], True)
        loss = last.compute_loss(params_list[-1], h, y, lmask)
        new_states.append(state_list[-1])
        loss = loss + _aux_losses(layers, new_states)
        return loss + _regularization(conf, params_list), new_states

    # ------------------------------------------------------------------- fit
    def _make_step(self):
        from deeplearning4j_tpu.nn.multilayer import make_train_step
        from deeplearning4j_tpu.parallel.compile_seam import compile_step
        # through the seam: plain jit strategy (params replicated; the stage
        # sharding lives inside PipelineParallel's own shard_map body), with
        # rule-set-attributed CompileTracker registration
        return compile_step(
            "PipelineTrainer.train_step",
            make_train_step(self.net.conf, loss=self._pipeline_loss),
            mesh=self.mesh, rule_set="pipeline", strategy="jit",
            conf=self.net.conf)

    #: batches staged + transferred ahead of the dispatch loop (see
    #: MultiLayerNetwork.prefetch_depth); 0 = synchronous staging
    prefetch_depth: int = 2

    @_dump_on_unhandled("PipelineTrainer.fit")
    def fit(self, iterator, epochs: int = 1) -> None:
        """Reference ParallelWrapper.fit(DataSetIterator):322 shape: every
        batch runs one pipelined train step; listeners fire per iteration.
        The next batch is staged + transferred on a background thread
        (DevicePrefetcher) while the current pipelined step executes."""
        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher

        net = self.net

        def stage(ds):
            if (getattr(ds, "features_mask", None) is not None
                    or getattr(ds, "labels_mask", None) is not None):
                # siblings fall back to net._fit_batch for masked batches;
                # the pipeline body threads no masks, so training here would
                # silently weight padded steps. Raised on the producer, the
                # error reaches the consumer AFTER every earlier batch ran —
                # same observable prefix as the synchronous loop.
                raise ValueError("PipelineTrainer does not support "
                                 "masked batches; use net.fit()")
            # lint: host-sync-in-hot-loop-ok (producer-thread staging; device_put is non-blocking)
            x = jax.device_put(np.asarray(ds.features))
            # lint: host-sync-in-hot-loop-ok (producer-thread staging; device_put is non-blocking)
            y = jax.device_put(np.asarray(ds.labels))
            return x, y

        if self._step is None:
            self._step = self._make_step()
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            pf = DevicePrefetcher(iterator, stage, depth=self.prefetch_depth,
                                  path="pipeline", wait_series=_t_staging)
            for x, y in pf:
                net.last_batch_size = int(x.shape[0]) if x.ndim else 0
                t0 = _time.perf_counter()
                (net.params_list, net.state_list, net.updater_state,
                 loss) = self._step(net.params_list, net.state_list,
                                    net.updater_state, x, y,
                                    net._next_rng(),
                                    jnp.int32(net.iteration))
                dt = _time.perf_counter() - t0
                _t_dispatch.observe(dt)
                _compile_tracker().note_step(fn="PipelineTrainer.train_step")
                _flight_recorder().record(
                    "step", path="PipelineTrainer.train_step",
                    it=net.iteration, batch=net.last_batch_size,
                    dispatch_s=dt)
                net.score_value = loss
                net.iteration += 1
                with _t_listeners.time():
                    for listener in net.listeners:
                        listener.iteration_done(net, net.iteration)
                _wd_beat(net.iteration)
