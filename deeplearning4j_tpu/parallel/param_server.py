"""Staleness-bounded asynchronous parameter-server training.

Reference: deeplearning4j-scaleout ParameterServerParallelWrapper.java — embeds
an Aeron media driver + ParameterServerNode (:159-161); worker threads
pushNDArray(model.params()) (:328) and re-fetch the global array (:305),
training asynchronously between syncs.

TPU-native redesign (stale-synchronous-parallel, not a thread toy):

* **Server** (`ParameterServer`): the canonical parameters live as ONE flat
  float32 vector behind a lock, with a monotonically increasing *version*.
  Workers push **deltas** (local params minus the base they pulled), not raw
  params; the server applies each delta through a server-side optimizer and
  bumps the version. A push whose base version is ``s`` behind is
  down-weighted by ``1/(1+s)``; pushes staler than ``staleness_cap`` are
  hard-rejected, forcing the worker to re-pull and rebase. This replaces the
  old ``(a+b)/2`` soft-average, where the *last* pusher always owned half
  the model regardless of worker count.

* **Transport** (`parallel/ps_transport.py`): one `Transport` API with two
  backends — ``inproc`` (direct calls, worker threads; deterministic tests)
  and ``tcp`` (stdlib sockets, length-prefixed frames, workers in separate
  OS processes so the GIL cannot mask the straggler win). Pushed deltas can
  ride the wire as bf16; canonical server state stays f32.

* **Overlap**: a double-buffered background pull (`_BackgroundPuller`, the
  DevicePrefetcher philosophy from datasets/prefetch.py) fetches fresh
  global params while the worker computes, so mid-window catch-up costs no
  worker wall-clock and staleness stays low.

The wrapper keeps the reference Builder API and grows it:
``.staleness(cap)``, ``.compression("bf16"|"none")``,
``.transport("inproc"|"tcp")``. Worker train steps compile through the
partition-rule seam (parallel/compile_seam.py) so they share CompileTracker
attribution with every other fit path.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import (
    ELASTIC_FENCED_PUSHES_TOTAL, PS_PULLS_TOTAL, PS_PUSHES_TOTAL,
    PS_PUSH_WEIGHT, PS_STALENESS, PS_VERSION, PS_WORKER_STEPS_TOTAL,
)
from deeplearning4j_tpu.observability.tracing import (
    current_span as _current_span,
    trace_span as _trace_span,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat

#: default hard staleness bound: a push based >8 versions back is rejected
DEFAULT_STALENESS_CAP = 8

_pushes = _obs_registry().counter(
    PS_PUSHES_TOTAL, "delta pushes by outcome (applied|rejected)")
_pushes_applied = _pushes.labels(outcome="applied")
_pushes_rejected = _pushes.labels(outcome="rejected")
_pulls = _obs_registry().counter(PS_PULLS_TOTAL,
                                 "server param pulls").labels()
_staleness_hist = _obs_registry().histogram(
    PS_STALENESS, "versions behind head at push time",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64)).labels()
_weight_hist = _obs_registry().histogram(
    PS_PUSH_WEIGHT, "staleness down-weight 1/(1+s) applied to each delta",
    buckets=(0.05, 0.1, 0.2, 0.3, 0.5, 0.8, 1.0)).labels()
_version_gauge = _obs_registry().gauge(
    PS_VERSION, "server param version (total applied pushes)").labels()
_worker_steps = _obs_registry().counter(
    PS_WORKER_STEPS_TOTAL, "local train steps by PS workers")
_fenced_pushes = _obs_registry().counter(
    ELASTIC_FENCED_PUSHES_TOTAL,
    "pushes rejected because the worker's membership epoch is dead "
    "(zombie fencing)").labels()


# --------------------------------------------------------------------------
# flat-vector codec: the whole param pytree as one contiguous f32 vector
# (what rides the wire and what the server owns)

@dataclass(frozen=True)
class TreeSpec:
    treedef: object
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[object, ...]
    sizes: Tuple[int, ...]


def flatten_tree(tree) -> Tuple[np.ndarray, TreeSpec]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    host = [np.asarray(leaf) for leaf in leaves]
    spec = TreeSpec(treedef=treedef,
                    shapes=tuple(a.shape for a in host),
                    dtypes=tuple(a.dtype for a in host),
                    sizes=tuple(a.size for a in host))
    if not host:
        return np.zeros(0, np.float32), spec
    vec = np.concatenate([a.astype(np.float32, copy=False).ravel()
                          for a in host])
    return vec, spec


def unflatten_tree(vec: np.ndarray, spec: TreeSpec, *, as_jax: bool = False):
    leaves, off = [], 0
    for shape, dtype, size in zip(spec.shapes, spec.dtypes, spec.sizes):
        a = vec[off:off + size].reshape(shape).astype(dtype, copy=False)
        leaves.append(jnp.asarray(a) if as_jax else a)
        off += size
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


# --------------------------------------------------------------------------
# server

@dataclass
class PushResult:
    """Outcome of one delta push. ``params``/``version`` always carry the
    post-push server state (a rejected push's forced re-pull rides the same
    round trip). ``fenced`` marks an epoch-fenced rejection: the pusher's
    membership lease is dead and no rebase/retry can ever succeed."""
    accepted: bool
    version: int
    staleness: int
    weight: float
    params: Optional[np.ndarray] = None
    fenced: bool = False


class StaleEpochFenced(RuntimeError):
    """The worker's membership epoch was fenced: its lease lapsed (or was
    superseded) and the server rejects its pushes permanently. The worker
    must exit; a replacement re-registers with a fresh epoch."""


class _ServerOptimizer:
    """Server-side update rule for pushed deltas (the PS analog of the
    reference ParameterServerNode's updater): plain SGD applies
    ``lr * weight * delta``; momentum folds deltas into a velocity first,
    smoothing bursty async arrivals."""

    def __init__(self, kind: str = "sgd", lr: float = 1.0,
                 momentum: float = 0.9):
        if kind not in ("sgd", "momentum"):
            raise ValueError(f"unknown server optimizer {kind!r}; "
                             "expected 'sgd' or 'momentum'")
        self.kind, self.lr, self.momentum = kind, lr, momentum
        self._vel: Optional[np.ndarray] = None

    def apply(self, params: np.ndarray, delta: np.ndarray,
              weight: float) -> np.ndarray:
        if self.kind == "sgd":
            params += (self.lr * weight) * delta
        else:
            if self._vel is None:
                self._vel = np.zeros_like(params)
            self._vel *= self.momentum
            self._vel += weight * delta
            params += self.lr * self._vel
        return params


class ParameterServer:
    """Versioned canonical param store (reference ParameterServerNode role).

    All mutation happens under one lock; ``version`` counts applied pushes.
    Thread-safe; the TCP front-end (`parallel/ps_transport.py`) serves the
    same object to out-of-process workers.
    """

    def __init__(self, initial_params, *,
                 staleness_cap: int = DEFAULT_STALENESS_CAP,
                 optimizer: str = "sgd", server_lr: float = 1.0,
                 momentum: float = 0.9, membership=None):
        vec, spec = flatten_tree(initial_params)
        self._vec = vec
        self._spec = spec
        self._opt = _ServerOptimizer(optimizer, server_lr, momentum)
        self._lock = threading.Lock()
        self.staleness_cap = int(staleness_cap)
        self.version = 0
        self.pushes = 0          # applied (legacy counter, kept public)
        self.rejected = 0
        #: cloud.MembershipOracle (or None): when set, pushes carrying a
        #: (member, epoch) identity are epoch-fenced against its leases
        self.membership = membership
        self.fenced = 0

    @property
    def spec(self) -> TreeSpec:
        return self._spec

    # ------------------------------------------------------------- core API
    def push_delta(self, delta: np.ndarray, base_version: int, *,
                   member: Optional[int] = None,
                   epoch: Optional[int] = None) -> PushResult:
        """Apply a worker delta computed against ``base_version``.

        staleness s = version - base_version; weight = 1/(1+s). A push with
        s > staleness_cap is rejected (weight 0) and the caller must rebase
        onto the returned fresh state before retrying.

        When a membership oracle is attached and the push carries a
        ``(member, epoch)`` identity, a dead/superseded epoch is fenced:
        rejected with ``fenced=True``, permanently — the zombie's delta must
        never land after its shard was handed off.
        """
        delta = np.asarray(delta, np.float32)
        if (self.membership is not None and member is not None
                and not self.membership.validate(member, epoch)):
            with self._lock:
                self.fenced += 1
                self.rejected += 1
                _fenced_pushes.inc()
                _pushes_rejected.inc()
                _flight_recorder().record(
                    "ps_push_fenced", member=member, epoch=epoch,
                    version=self.version)
                return PushResult(False, self.version,
                                  self.version - int(base_version), 0.0,
                                  np.copy(self._vec), fenced=True)
        with self._lock:
            staleness = self.version - int(base_version)
            _staleness_hist.observe(staleness)
            if staleness > self.staleness_cap:
                self.rejected += 1
                _pushes_rejected.inc()
                _flight_recorder().record(
                    "ps_push_rejected", staleness=staleness,
                    cap=self.staleness_cap, version=self.version)
                return PushResult(False, self.version, staleness, 0.0,
                                  np.copy(self._vec))
            weight = 1.0 / (1.0 + max(0, staleness))
            self._vec = self._opt.apply(self._vec, delta, weight)
            self.version += 1
            self.pushes += 1
            _pushes_applied.inc()
            _weight_hist.observe(weight)
            _version_gauge.set(self.version)
            _wd_beat(self.version)
            return PushResult(True, self.version, staleness, weight,
                              np.copy(self._vec))

    def pull_flat(self) -> Tuple[int, np.ndarray]:
        _pulls.inc()
        with self._lock:
            return self.version, np.copy(self._vec)

    # ------------------------------------------------- legacy pytree facade
    def push(self, params, base_version: Optional[int] = None) -> PushResult:
        """Full-param push (the pre-engine API): converted to a delta against
        the caller's base — or, when no base version is known, against the
        current head (last-writer-wins at weight 1, staleness 0)."""
        vec, _ = flatten_tree(params)
        with self._lock:
            head = np.copy(self._vec)
            base = self.version if base_version is None else base_version
        return self.push_delta(vec - head, base)

    def pull(self):
        _, vec = self.pull_flat()
        return unflatten_tree(vec, self._spec)


# --------------------------------------------------------------------------
# hooks (unchanged SPI)

class ParameterServerTrainingHook:
    """Training-hook SPI (reference dl4j-spark-parameterserver
    ParameterServerTrainingHook.java): callbacks around each worker's local
    update so custom logic (gradient compression, auditing, custom sync) can
    interpose on the async training path."""

    def pre_update(self, dataset, model) -> None:
        pass

    def post_update(self, dataset, model) -> None:
        pass


# --------------------------------------------------------------------------
# background pull: double-buffered fetch that overlaps local compute

class _BackgroundPuller:
    """Fetch fresh (version, params) on a daemon thread while the worker
    computes (DevicePrefetcher philosophy: the transfer hides behind the
    step). `latest()` is non-blocking; `request()` forces an immediate
    fetch (the wake event fires regardless of where the thread is in its
    wait); between requests the thread polls at ``poll_interval_s``,
    doubling the interval up to ``idle_backoff_cap_s`` while the server
    version is NOT advancing — an idle fleet stops burning CPU on no-change
    pulls — and snapping back to the base interval on any fresh version or
    explicit request."""

    def __init__(self, pull_fn: Callable[[], Tuple[int, np.ndarray]],
                 poll_interval_s: float = 0.05,
                 idle_backoff_cap_s: float = 0.8):
        self._pull = pull_fn
        self._interval = poll_interval_s
        self._idle_cap = max(poll_interval_s, idle_backoff_cap_s)
        self._buf: Optional[Tuple[int, np.ndarray]] = None
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        wait = self._interval
        last_version = -1
        while True:
            requested = self._wake.wait(wait)
            self._wake.clear()
            if self._stop:
                return
            try:
                got = self._pull()
            except (OSError, RuntimeError) as e:
                # transport teardown race at fit() shutdown: the worker
                # falls back to its push-ack state; nothing to propagate
                _flight_recorder().record("ps_bg_pull_error", error=str(e))
                continue
            fresh = got[0] > last_version
            last_version = max(last_version, got[0])
            with self._lock:
                if self._buf is None or got[0] > self._buf[0]:
                    self._buf = got
            # exponential idle backoff: only stale no-request polls widen
            # the interval; data or a request() resets it immediately
            if requested or fresh:
                wait = self._interval
            else:
                wait = min(wait * 2.0, self._idle_cap)

    def request(self) -> None:
        self._wake.set()

    def latest(self) -> Optional[Tuple[int, np.ndarray]]:
        with self._lock:
            buf, self._buf = self._buf, None
        return buf

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)


# --------------------------------------------------------------------------
# worker loop (shared by in-process threads and `python -m ...ps_worker`)

def run_worker_loop(*, transport, replica, step_fn, next_batch,
                    push_frequency: int,
                    hooks: Sequence[ParameterServerTrainingHook] = (),
                    delay_s: float = 0.0, worker_id: int = 0,
                    background_pull: bool = True,
                    on_push: Optional[Callable[[bool], None]] = None) -> dict:
    """Train ``replica`` on batches from ``next_batch()`` (None = done),
    pushing a delta every ``push_frequency`` steps; returns worker stats.

    ``step_fn(params, states, upd, x, y, rng, it) -> (params, states, upd,
    loss)`` is the compiled train step; pass None to fall back to
    ``replica.fit`` (non-MultiLayerNetwork models).
    ``delay_s`` is the per-step fault-injection sleep used by the straggler
    benchmarks/tests.
    ``on_push(accepted)`` fires after each push window resolves — the
    elastic worker commits its broker offsets there, so samples are marked
    consumed only once their delta landed (at-least-once accounting).
    An epoch-fenced push raises ``StaleEpochFenced`` immediately: the
    worker's lease is dead, retrying cannot help, and training on must not
    continue (its shard now belongs to a replacement).
    """
    spec = None
    version, base_vec = transport.pull()
    steps = pushes = rejected = rebased = 0
    steps_since_push = 0
    step_series = _worker_steps.labels(worker=str(worker_id))

    def _set_replica(vec: np.ndarray) -> None:
        nonlocal spec
        if spec is None:
            _, spec = flatten_tree(replica.params_list)
        replica.params_list = unflatten_tree(vec, spec, as_jax=True)

    _set_replica(base_vec)
    # the puller gets its OWN connection when the transport supports it
    # (tcp), so background fetches genuinely overlap pushes on the wire
    bg_transport = (transport.clone() if background_pull
                    and hasattr(transport, "clone") else transport)
    puller = (_BackgroundPuller(bg_transport.pull)
              if background_pull else None)
    if puller is not None:
        puller.request()

    def _push_window() -> None:
        # nest the window's push RPC(s) under one span parented by the
        # batch's consume span (bound on the transport by the elastic
        # worker); with no parent, open no span — a static worker would
        # only mint root-trace noise
        parent = _current_span() or getattr(transport, "trace_parent", None)
        if parent is None:
            return _push_window_inner()
        with _trace_span("ps.push_window", parent=parent,
                         worker=str(worker_id)):
            return _push_window_inner()

    def _push_window_inner() -> None:
        nonlocal version, base_vec, steps_since_push, pushes, rejected
        local, _ = flatten_tree(replica.params_list)
        delta = local - base_vec
        # pre-push rebase: a delta is position-independent (the server
        # applies head + w*delta), so the freshest background-pulled
        # version is this window's honest base — global progress the
        # worker has already seen must not count against it as staleness
        if puller is not None:
            got = puller.latest()
            if got is not None and got[0] > version:
                version = got[0]
        res = transport.push(delta, version)
        if getattr(res, "fenced", False):
            raise StaleEpochFenced(
                f"worker {worker_id}: push fenced at version {res.version}")
        if not res.accepted:
            # hard-rejected: rebase the local window onto the forced
            # re-pull state, then re-push at ~zero staleness
            rejected += 1
            res2 = transport.push(delta, res.version)
            if getattr(res2, "fenced", False):
                raise StaleEpochFenced(
                    f"worker {worker_id}: push fenced at version "
                    f"{res2.version}")
            res = res2 if res2.accepted else res
        if res.accepted:
            pushes += 1
        version, base_vec = res.version, res.params
        _set_replica(base_vec)
        steps_since_push = 0
        if on_push is not None:
            on_push(res.accepted)
        if puller is not None:
            puller.request()

    try:
        while True:
            ds = next_batch()
            if ds is None:
                break
            if delay_s > 0.0:
                time.sleep(delay_s)
            # mid-window catch-up from the background pull: fold fresh
            # global progress under the local window without blocking or
            # re-counting it
            if puller is not None and steps_since_push > 0:
                got = puller.latest()
                if got is not None and got[0] > version:
                    local, _ = flatten_tree(replica.params_list)
                    version, fresh = got
                    _set_replica(fresh + (local - base_vec))
                    base_vec = fresh
                    rebased += 1
                    puller.request()
            for hook in hooks:
                hook.pre_update(ds, replica)
            if step_fn is not None:
                p, s, u, loss = step_fn(
                    replica.params_list, replica.state_list,
                    replica.updater_state, jnp.asarray(ds.features),
                    jnp.asarray(ds.labels), replica._next_rng(),
                    jnp.int32(replica.iteration))
                replica.params_list, replica.state_list = p, s
                replica.updater_state = u
                replica.score_value = loss
            else:
                replica.fit(ds.features, ds.labels)
            replica.iteration += 1
            for hook in hooks:
                hook.post_update(ds, replica)
            steps += 1
            steps_since_push += 1
            step_series.inc()
            _compile_tracker().note_step(fn=f"ps_worker[{worker_id}]")
            if steps_since_push >= push_frequency:
                _push_window()
        # flush ONLY a partial window: a worker that pushed at the boundary
        # has nothing left, and re-pushing its last delta would double-count
        # it (the pre-engine shutdown bug)
        if steps_since_push > 0:
            _push_window()
    finally:
        # the puller must die even on a fenced/crashed exit, or its daemon
        # thread keeps hammering the transport after the worker is gone
        if puller is not None:
            puller.stop()
            if bg_transport is not transport:
                bg_transport.close()
    return {"worker_id": worker_id, "steps": steps, "pushes": pushes,
            "rejected": rejected, "rebased": rebased,
            "final_version": version}


def make_compiled_worker_step(net, *, transport: str):
    """Compile the replica train step through the partition-rule seam
    (single-replica program: every input replicated on the worker's device;
    CompileTracker attribution rides the seam). Returns None for models
    without a MultiLayerNetwork-style train step — the worker loop then
    falls back to ``replica.fit``."""
    from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                                  make_train_step)
    if not isinstance(net, MultiLayerNetwork):
        return None
    from deeplearning4j_tpu.parallel.compile_seam import compile_step
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    return compile_step(
        "ParameterServerParallelWrapper.worker_step",
        make_train_step(net.conf), mesh=data_parallel_mesh(),
        rule_set="ps_async", strategy="jit",
        cache_key=(transport,), conf=net.conf)


# --------------------------------------------------------------------------
# wrapper

class ParameterServerParallelWrapper:
    """Async-DP trainer (reference ParameterServerParallelWrapper.java)."""

    def __init__(self, model, workers: int = 2, push_frequency: int = 4,
                 prefetch: int = 2,
                 training_hooks: Optional[List[ParameterServerTrainingHook]] = None,
                 staleness: int = DEFAULT_STALENESS_CAP,
                 compression: str = "none",
                 transport: str = "inproc",
                 server_optimizer: str = "sgd", server_lr: float = 1.0,
                 worker_delays: Optional[Sequence[float]] = None):
        if transport not in ("inproc", "tcp", "shm"):
            raise ValueError(f"unknown transport {transport!r}; "
                             "expected 'inproc', 'tcp' or 'shm'")
        if compression not in ("none", "bf16"):
            raise ValueError(f"unknown compression {compression!r}; "
                             "expected 'none' or 'bf16'")
        if transport in ("tcp", "shm") and training_hooks:
            raise ValueError(
                "training hooks run in the worker's interpreter; the tcp "
                "transport trains in separate processes — use inproc")
        self.model = model
        self.workers = workers
        self.push_frequency = max(1, push_frequency)
        self.prefetch = prefetch
        self.training_hooks = list(training_hooks or [])
        self.staleness = int(staleness)
        self.compression = compression
        self.transport = transport
        self.server_optimizer = server_optimizer
        self.server_lr = server_lr
        self.worker_delays = list(worker_delays or [])
        self.worker_stats: List[dict] = []
        self.server: Optional[ParameterServer] = None
        self._compiled_step = None  # one program per wrapper: repeated
        # fit() calls must not re-trace (recompile-storm hygiene)

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def workers(self, n: int):
            self._kw["workers"] = n
            return self

        def push_frequency(self, n: int):
            self._kw["push_frequency"] = n
            return self

        def training_hooks(self, *hooks):
            self._kw["training_hooks"] = list(hooks)
            return self

        def staleness(self, cap: int):
            """Hard staleness bound τ: pushes based more than τ versions
            behind are rejected (weight already decays as 1/(1+s))."""
            self._kw["staleness"] = cap
            return self

        def compression(self, codec: str):
            """Wire codec for pushed deltas: "bf16" halves push bytes."""
            self._kw["compression"] = codec
            return self

        def transport(self, kind: str):
            """"inproc" (worker threads), "tcp" (worker processes over
            loopback sockets), or "shm" (worker processes; tensor bytes in
            shared-memory rings, control verbs on the socket — falls back
            to tcp frames when segments can't attach)."""
            self._kw["transport"] = kind
            return self

        def server_optimizer(self, kind: str, lr: float = 1.0):
            self._kw["server_optimizer"] = kind
            self._kw["server_lr"] = lr
            return self

        def worker_delays(self, *delays: float):
            """Fault injection for benchmarks/tests: worker i sleeps
            delays[i] seconds before every local step (straggler model)."""
            self._kw["worker_delays"] = list(delays)
            return self

        def build(self) -> "ParameterServerParallelWrapper":
            return ParameterServerParallelWrapper(self._model, **self._kw)

    @staticmethod
    def builder(model) -> "ParameterServerParallelWrapper.Builder":
        return ParameterServerParallelWrapper.Builder(model)

    # ------------------------------------------------------------------ fit
    @_dump_on_unhandled("ParameterServerParallelWrapper.fit")
    def fit(self, iterator, epochs: int = 1) -> None:
        self.server = ParameterServer(
            self.model.params_list, staleness_cap=self.staleness,
            optimizer=self.server_optimizer, server_lr=self.server_lr)
        if self.transport in ("tcp", "shm"):
            self._fit_tcp(iterator, epochs)
        else:
            self._fit_inproc(iterator, epochs)
        self.model.params_list = unflatten_tree(
            self.server.pull_flat()[1], self.server.spec, as_jax=True)
        # lint: host-sync-in-hot-loop-ok (one trusted LazyScore sync after the workers join)
        self.model.score_value = float(self.model.score_value)

    def _delay(self, worker_id: int) -> float:
        if worker_id < len(self.worker_delays):
            return float(self.worker_delays[worker_id])
        return 0.0

    def _fit_inproc(self, iterator, epochs: int) -> None:
        import queue as _queue

        model = self.model
        server = self.server
        if self._compiled_step is None:
            self._compiled_step = make_compiled_worker_step(
                model, transport="inproc")
        step = self._compiled_step
        q: _queue.Queue = _queue.Queue(maxsize=self.workers * max(
            1, self.prefetch))
        failed: List[BaseException] = []
        self.worker_stats = [None] * self.workers

        def make_worker(worker_id: int):
            def run():
                from deeplearning4j_tpu.parallel.ps_transport import (
                    InprocTransport)
                replica = model.clone() if hasattr(model, "clone") else model

                def next_batch():
                    ds = q.get()
                    q.task_done()
                    return ds

                try:
                    self.worker_stats[worker_id] = run_worker_loop(
                        transport=InprocTransport(server), replica=replica,
                        step_fn=(step.fn if step is not None else None),
                        next_batch=next_batch,
                        push_frequency=self.push_frequency,
                        hooks=self.training_hooks,
                        delay_s=self._delay(worker_id),
                        worker_id=worker_id)
                except BaseException as e:
                    failed.append(e)
                    _flight_recorder().record(
                        "ps_worker_crash", worker=worker_id, error=repr(e))
                    raise
            return threading.Thread(target=run, daemon=True,
                                    name=f"ps-worker-{worker_id}")

        threads = [make_worker(i) for i in range(self.workers)]
        for t in threads:
            t.start()
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                while not failed:
                    try:
                        q.put(ds, timeout=1.0)
                        break
                    except _queue.Full:
                        continue
                if failed:
                    break
        for _ in threads:
            q.put(None)
        for t in threads:
            t.join()
        if failed:
            raise RuntimeError("parameter-server worker crashed") from failed[0]

    def _fit_tcp(self, iterator, epochs: int) -> None:
        """Separate-process workers over loopback TCP (the pattern proven by
        tests/test_distributed_multiprocess.py): the iterator's batches are
        materialized, round-robin partitioned, and shipped to each worker —
        through a shared-memory segment on the "shm" transport (no
        compression, no filesystem round-trip; npz tempfile fallback if the
        host has no usable /dev/shm), as an .npz otherwise; model config
        rides as JSON; workers pull initial params from this process's
        server."""
        import json
        import os
        import subprocess
        import sys
        import tempfile

        from deeplearning4j_tpu.nn.conf.serde import to_json
        from deeplearning4j_tpu.parallel import ps_transport as _pst

        batches = []
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            batches.extend(iterator)
        shards = [batches[i::self.workers] for i in range(self.workers)]

        frontend = _pst.ParameterServerTcpFrontend(self.server).start()
        procs = []
        segments: List[str] = []
        try:
            with tempfile.TemporaryDirectory(prefix="dl4j_ps_") as tmp:
                conf_path = os.path.join(tmp, "conf.json")
                with open(conf_path, "w") as f:
                    f.write(to_json(self.model.conf))
                env = os.environ.copy()
                env["JAX_PLATFORMS"] = "cpu"
                env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU relay in workers
                env.pop("XLA_FLAGS", None)  # one CPU device per process
                repo_root = os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
                env["PYTHONPATH"] = (repo_root + os.pathsep
                                     + env.get("PYTHONPATH", ""))
                for i, shard in enumerate(shards):
                    x = np.stack([np.asarray(d.features)  # lint: host-sync-in-hot-loop-ok (one-time shard serialization before workers spawn, not a train loop)
                                  for d in shard])
                    y = np.stack([np.asarray(d.labels)  # lint: host-sync-in-hot-loop-ok (one-time shard serialization before workers spawn, not a train loop)
                                  for d in shard])
                    data_path = None
                    if self.transport == "shm":
                        try:
                            seg = _pst.write_shard_segment(
                                {"x": x, "y": y}, kind=f"shard{i}")
                            segments.append(seg)
                            data_path = "shm://" + seg
                        except OSError:
                            data_path = None  # fall through to npz
                    if data_path is None:
                        data_path = os.path.join(tmp, f"worker{i}.npz")
                        np.savez(data_path, x=x, y=y)
                    cmd = [sys.executable, "-m",
                           "deeplearning4j_tpu.parallel.ps_worker",
                           "--addr", f"127.0.0.1:{frontend.port}",
                           "--conf", conf_path, "--data", data_path,
                           "--worker-id", str(i),
                           "--push-frequency", str(self.push_frequency),
                           "--codec", self.compression,
                           "--ps-transport", self.transport,
                           "--delay", str(self._delay(i))]
                    procs.append(subprocess.Popen(
                        cmd, env=env, stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, text=True))
                self.worker_stats = []
                for i, p in enumerate(procs):
                    stdout, stderr = p.communicate(timeout=600)
                    if p.returncode != 0:
                        raise RuntimeError(
                            f"tcp PS worker {i} failed (rc={p.returncode}):\n"
                            + stderr[-2000:])
                    self.worker_stats.append(
                        json.loads(stdout.strip().splitlines()[-1]))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            frontend.stop()
            for seg in segments:
                _pst.release_segment_by_name(seg)
