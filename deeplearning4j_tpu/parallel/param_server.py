"""Asynchronous parameter-server training.

Reference: deeplearning4j-scaleout ParameterServerParallelWrapper.java — embeds
an Aeron media driver + ParameterServerNode (:159-161); worker threads
pushNDArray(model.params()) (:328) and re-fetch the global array (:305),
training asynchronously between syncs.

TPU-native redesign: the UDP media driver becomes an in-process server object
holding the canonical param pytree behind a lock (multi-host deployments would
put this behind jax.distributed; the push/pull semantics are identical).
Workers run in threads, each training a model replica; every
``push_frequency`` iterations a worker pushes its params (server soft-averages
them into the global copy) and pulls the fresh global state.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax
import numpy as np



class ParameterServer:
    """In-process async parameter store (reference ParameterServerNode role)."""

    def __init__(self, initial_params):
        self._params = jax.tree_util.tree_map(np.asarray, initial_params)
        self._lock = threading.Lock()
        self.pushes = 0

    def push(self, params) -> None:
        """Soft-average the pushed params into the global copy
        (the reference's PS averages concurrent worker pushes the same way)."""
        incoming = jax.tree_util.tree_map(np.asarray, params)
        with self._lock:
            self._params = jax.tree_util.tree_map(
                lambda a, b: (a + b) / 2.0, self._params, incoming)
            self.pushes += 1

    def pull(self):
        with self._lock:
            return jax.tree_util.tree_map(np.copy, self._params)


class ParameterServerTrainingHook:
    """Training-hook SPI (reference dl4j-spark-parameterserver
    ParameterServerTrainingHook.java): callbacks around each worker's local
    update so custom logic (gradient compression, auditing, custom sync) can
    interpose on the async training path."""

    def pre_update(self, dataset, model) -> None:
        pass

    def post_update(self, dataset, model) -> None:
        pass


class ParameterServerParallelWrapper:
    """Async-DP trainer (reference ParameterServerParallelWrapper.java)."""

    def __init__(self, model, workers: int = 2, push_frequency: int = 4,
                 prefetch: int = 2,
                 training_hooks: Optional[List[ParameterServerTrainingHook]] = None):
        self.model = model
        self.workers = workers
        self.push_frequency = max(1, push_frequency)
        self.prefetch = prefetch
        self.training_hooks = list(training_hooks or [])

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def workers(self, n: int):
            self._kw["workers"] = n
            return self

        def push_frequency(self, n: int):
            self._kw["push_frequency"] = n
            return self

        def training_hooks(self, *hooks):
            self._kw["training_hooks"] = list(hooks)
            return self

        def build(self) -> "ParameterServerParallelWrapper":
            return ParameterServerParallelWrapper(self._model, **self._kw)

    @staticmethod
    def builder(model) -> "ParameterServerParallelWrapper.Builder":
        return ParameterServerParallelWrapper.Builder(model)

    def fit(self, iterator, epochs: int = 1) -> None:
        import queue as _queue

        model = self.model
        server = ParameterServer(model.params_list)
        q: _queue.Queue = _queue.Queue(maxsize=self.workers * self.prefetch)

        def make_worker(worker_id: int):
            def run():
                replica = model.clone() if hasattr(model, "clone") else model
                local_iters = 0
                while True:
                    ds = q.get()
                    if ds is None:
                        q.task_done()
                        break
                    replica.params_list = jax.tree_util.tree_map(
                        jax.numpy.asarray, server.pull()) \
                        if local_iters % self.push_frequency == 0 \
                        else replica.params_list
                    for hook in self.training_hooks:
                        hook.pre_update(ds, replica)
                    replica.fit(ds.features, ds.labels)
                    for hook in self.training_hooks:
                        hook.post_update(ds, replica)
                    local_iters += 1
                    if local_iters % self.push_frequency == 0:
                        server.push(replica.params_list)
                    q.task_done()
                server.push(replica.params_list)
            return threading.Thread(target=run, daemon=True)

        threads: List[threading.Thread] = [make_worker(i)
                                           for i in range(self.workers)]
        for t in threads:
            t.start()
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                q.put(ds)
        for _ in threads:
            q.put(None)
        for t in threads:
            t.join()
        model.params_list = jax.tree_util.tree_map(jax.numpy.asarray,
                                                   server.pull())
        # lint: host-sync-in-hot-loop-ok (one trusted LazyScore sync after the workers join)
        model.score_value = float(model.score_value)
