"""Long-context attention: ring (context-parallel) and Ulysses (all-to-all).

The reference scales sequence length only via truncated BPTT + masking
(SURVEY.md §5; reference MultiLayerNetwork.doTruncatedBPTT:1140) — sequence/
context parallelism does not exist there. These are the TPU-native long-context
mechanisms required as first-class components:

* ``ring_attention``: queries stay resident; K/V shards rotate around the ICI
  ring via ``ppermute`` while each device accumulates its attention output with
  an online (flash-style) softmax — memory per device stays O(T/N), and the
  K/V transfer overlaps the local block computation in XLA's schedule.
* ``ulysses_attention``: all-to-all swaps the sequence shard for a head shard,
  computes full-sequence attention on 1/N of the heads, then swaps back.

Both are exact: outputs match single-device softmax attention to fp tolerance.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from deeplearning4j_tpu.parallel.partition import (
    pspec as P, named_sharding as _named_sharding,
)
from deeplearning4j_tpu.jax_compat import pcast, shard_map
from deeplearning4j_tpu.observability.names import COLLECTIVE_BYTES_PER_STEP
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry, tree_nbytes as _tree_nbytes,
)

Array = jax.Array
_NEG = -1e30

# trace-time traffic accounting: these entry points run INSIDE jit traces,
# so a per-execution counter is impossible — instead each (re)trace sizes
# the collective from the static operand shapes and records a per-step gauge
_collective_per_step = _obs_registry().gauge(
    COLLECTIVE_BYTES_PER_STEP,
    "bytes one executed step moves through a traced collective, from "
    "static shapes at trace time, by op and site")


def attention_reference(q: Array, k: Array, v: Array, causal: bool = False) -> Array:
    """Plain full-sequence softmax attention (the correctness oracle).

    Shapes: q,k,v = (B, T, H, D) -> (B, T, H, D).
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _online_block(q, k, v, m_prev, l_prev, o_prev, q_off, kv_off, causal):
    """One flash-attention accumulation step against a K/V block.

    q: (B, Tq, H, D); k,v: (B, Tk, H, D); m,l: (B, H, Tq); o: (B, Tq, H, D).
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        q_pos = q_off + jnp.arange(tq)
        kv_pos = kv_off + jnp.arange(tk)
        mask = q_pos[:, None] >= kv_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    m_blk = jnp.max(s, axis=-1)                      # (B, H, Tq)
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(s - m_new[..., None])                # (B, H, Tq, Tk)
    # fully-masked blocks: keep them exactly zero
    p = jnp.where(s <= _NEG, 0.0, p)
    scale = jnp.exp(m_prev - m_new)                  # (B, H, Tq)
    l_new = l_prev * scale + jnp.sum(p, axis=-1)
    o_scaled = o_prev * jnp.transpose(scale, (0, 2, 1))[..., None]
    o_new = o_scaled + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          batch_axis=None):
    """Per-shard body: rotate K/V around the ring, accumulate online softmax."""
    n_dev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    q_off = my_idx * Tq

    # accumulators are device-varying (they depend on this shard's q) — mark
    # them so the fori_loop carry types line up under shard_map (over the
    # batch axis too when the leading dim is data-sharded)
    axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
    vary = lambda x: pcast(x, axes, to="varying")
    m = vary(jnp.full((B, H, Tq), _NEG, q.dtype))
    l = vary(jnp.zeros((B, H, Tq), q.dtype))
    o = jnp.zeros_like(q)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        # K/V chunk currently resident arrived from (my_idx - step) % n_dev
        src = (my_idx - step) % n_dev
        kv_off = src * Tk
        m, l, o = _online_block(q, k_cur, v_cur, m, l, o, q_off, kv_off, causal)
        # rotate for the next step (last rotation is redundant but keeps the
        # loop shape static; XLA overlaps it with the block compute)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    m, l, o, _, _ = lax.fori_loop(0, n_dev, body, (m, l, o, k, v))
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]     # (B, Tq, H, 1)
    return o / jnp.maximum(l_t, 1e-20)


def ring_attention_sharded(q: Array, k: Array, v: Array, mesh: Mesh,
                           axis_name: str = "sp", causal: bool = False,
                           batch_axis: str = None) -> Array:
    """Trace-safe ring attention: callable from inside a jitted train step
    (no device_put — under jit, GSPMD reshards operands to the shard_map's
    in_specs). This is what attention layers dispatch when an active
    ParallelContext declares ``seq_mode="ring"`` (parallel/context.py).
    ``batch_axis`` shards the leading (batch) dim too, so composing with
    data parallelism never replicates attention work across DP replicas."""
    spec = P(batch_axis, axis_name)
    # each ring step rotates the full K/V through ppermute once per device;
    # total traffic per executed attention = global K+V bytes
    _collective_per_step.labels(op="ppermute_kv",
                                site="ring_attention").set(
        _tree_nbytes((k, v)))
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, batch_axis=batch_axis),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                   axis_name: str = "sp", causal: bool = False) -> Array:
    """Exact context-parallel attention over the mesh's ``axis_name`` axis.

    Inputs are (B, T, H, D) with T sharded over ``axis_name`` (global arrays or
    host arrays; sharding is applied here). Returns output sharded the same way.
    """
    sh = _named_sharding(mesh, P(None, axis_name))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    return ring_attention_sharded(q, k, v, mesh, axis_name, causal)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   interpret: bool = False):
    """All-to-all: (T/N, H) -> (T, H/N), full attention, swap back
    (DeepSpeed-Ulysses sequence parallelism)."""
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    # (B, T/N, H, D) -> (B, T, H/N, D)
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # full-sequence attention on 1/N of the heads. This body is built with
    # check_vma=False (see ulysses_attention_sharded), so the pallas flash
    # kernel ENGAGES here on TPU — O(blk*T) attention memory per device for
    # the gathered sequence; below the kernel's dispatch thresholds (or off
    # TPU) the same call runs the identical XLA math at O(T^2) scores memory.
    # interpret lets tests exercise the pallas-under-shard_map path on CPU.
    og = flash_attention(qg, kg, vg, causal, interpret)
    return lax.all_to_all(og, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(q: Array, k: Array, v: Array, mesh: Mesh,
                              axis_name: str = "sp", causal: bool = False,
                              interpret: bool = False,
                              batch_axis: str = None) -> Array:
    """Trace-safe Ulysses attention (see ring_attention_sharded): the
    in-jit dispatch target for sequence-parallel attention layers."""
    n = mesh.shape[axis_name]
    if q.shape[2] % n != 0:  # lint: recompile-hazard-ok (trace-time config validation; head count is static under jit)
        raise ValueError(f"num heads {q.shape[2]} not divisible by axis size {n}")
    # four all-to-alls (q/k/v gather + output scatter), each moving one
    # q-sized global array across the axis
    _collective_per_step.labels(op="all_to_all",
                                site="ulysses_attention").set(
        4 * _tree_nbytes(q))
    spec = P(batch_axis, axis_name)
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # annotation, so the flash kernel inside the body can't satisfy the vma
    # checker; correctness is pinned by the =reference tests instead
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name, causal=causal,
                          interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q: Array, k: Array, v: Array, mesh: Mesh,
                      axis_name: str = "sp", causal: bool = False,
                      interpret: bool = False) -> Array:
    """Sequence-parallel attention via head-sharding all-to-all. Requires the
    head count to be divisible by the axis size."""
    sh = _named_sharding(mesh, P(None, axis_name))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    return ulysses_attention_sharded(q, k, v, mesh, axis_name, causal,
                                     interpret)
