"""Active parallelism context: how layers discover the mesh they run under.

The reference's standard is that parallelism WRAPS the model API — a user
hands any net to ParallelWrapper (reference ParallelWrapper.java:44) or
SparkDl4jMultiLayer and the same model code runs distributed. Rounds 1-4
met that bar for data/tensor parallelism but left sequence, expert and
pipeline parallelism as hand-written shard_map demos. This module closes
the gap: a trainer (ParallelWrapper, PipelineTrainer) publishes the active
mesh + axis roles here while it TRACES its jitted train step, and the
attention/MoE layers consult it inside ``apply`` to dispatch the
sequence-parallel attention (parallel/ring_attention.py) or the GShard
all_to_all expert path (parallel/moe.py) instead of their single-device
math. Because layer ``apply`` bodies execute at trace time, an ordinary
Python context manager is enough — no config plumbing through every layer.

Layers must treat the context as read-only and fall back to their dense
path when it is absent (single-device training, gradient checks).
"""
from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from typing import Optional

from jax.sharding import Mesh

_state = threading.local()


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """Mesh + axis roles for the training step currently being traced.

    ``seq_axis``: mesh axis the sequence (time) dimension is parallelized
    over; attention layers dispatch ring/Ulysses attention over it.
    ``seq_mode``: "ulysses" (all_to_all head swap — exact, best when heads
    divide the axis) or "ring" (ppermute K/V rotation — O(T/N) memory).
    ``expert_axis``: mesh axis experts + tokens are sharded over for MoE
    all_to_all dispatch (conventionally the data axis doubles as it).
    ``interpret``: run Pallas kernels inside sequence-parallel bodies in
    interpret mode (CPU test meshes).
    """

    mesh: Mesh
    seq_axis: Optional[str] = None
    seq_mode: str = "ulysses"
    expert_axis: Optional[str] = None
    capacity_factor: float = 2.0
    interpret: bool = False
    #: mesh axis the BATCH dim is sharded over (the DP axis). SP/EP bodies
    #: shard their leading dim over it too, so data-parallel replicas never
    #: redundantly recompute each other's attention/FFN work.
    data_axis: Optional[str] = None

    def __post_init__(self):
        for ax in (self.seq_axis, self.expert_axis, self.data_axis):
            if ax is not None and ax not in self.mesh.shape:
                raise ValueError(f"axis {ax!r} not in mesh axes "
                                 f"{tuple(self.mesh.shape)}")
        if self.seq_mode not in ("ulysses", "ring"):
            raise ValueError(f"unknown seq_mode {self.seq_mode!r}")


def current() -> Optional[ParallelContext]:
    """The context of the train step being traced right now, or None."""
    return getattr(_state, "ctx", None)


@contextmanager
def parallel_context(mesh: Mesh, *, seq_axis: Optional[str] = None,
                     seq_mode: str = "ulysses",
                     expert_axis: Optional[str] = None,
                     capacity_factor: float = 2.0,
                     interpret: bool = False,
                     data_axis: Optional[str] = None):
    """Publish the active mesh/axes while tracing a distributed train step."""
    prev = current()
    _state.ctx = ParallelContext(mesh, seq_axis=seq_axis, seq_mode=seq_mode,
                                 expert_axis=expert_axis,
                                 capacity_factor=capacity_factor,
                                 interpret=interpret, data_axis=data_axis)
    try:
        yield _state.ctx
    finally:
        _state.ctx = prev
