"""Parameter-server transports: one API, two interchangeable backends.

The worker loop (param_server.run_worker_loop) only sees ``pull()`` and
``push(delta, base_version)``:

* ``InprocTransport`` — direct method calls into a shared `ParameterServer`
  (worker threads; deterministic, zero-copy; what the unit tests use).
* ``TcpTransport`` + ``ParameterServerTcpFrontend`` — stdlib sockets with
  length-prefixed framed messages (streaming/wire.py), workers in separate
  OS processes so the GIL cannot mask the async win. Pushed deltas may ride
  as bf16 (`codec="bf16"`); pull responses and the canonical store stay f32.

The reference's Aeron media driver + ParameterServerNode pair maps onto
frontend + server object; replacing UDP with framed loopback TCP keeps the
protocol inspectable with nothing beyond the stdlib.
"""
from __future__ import annotations

import socket
import threading
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import PS_WIRE_BYTES_TOTAL
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.parallel.param_server import (
    ParameterServer, PushResult,
)
from deeplearning4j_tpu.streaming import wire

_wire_bytes = _obs_registry().counter(
    PS_WIRE_BYTES_TOTAL, "PS bytes on the wire, by op and codec")


class Transport:
    """What a PS worker holds: pull the versioned global params, push a
    delta against the version it pulled."""

    def pull(self) -> Tuple[int, np.ndarray]:
        raise NotImplementedError

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InprocTransport(Transport):
    def __init__(self, server: ParameterServer):
        self._server = server

    def pull(self) -> Tuple[int, np.ndarray]:
        return self._server.pull_flat()

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        return self._server.push_delta(delta, base_version)


class TcpTransport(Transport):
    """Client side of the framed loopback protocol. NOT thread-safe: each
    worker (and its background puller) opens its own connection via
    ``clone()``."""

    def __init__(self, addr: Tuple[str, int], codec: str = "none",
                 timeout: float = 60.0):
        self._addr = tuple(addr)
        self._codec = codec
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock = wire.connect(self._addr, timeout=timeout)
        self._tx = _wire_bytes.labels(op="push", codec=codec)
        self._rx = _wire_bytes.labels(op="pull", codec="none")

    def clone(self) -> "TcpTransport":
        return TcpTransport(self._addr, self._codec, self._timeout)

    def pull(self) -> Tuple[int, np.ndarray]:
        with self._lock:
            reply, payload, _ = wire.request(self._sock, {"op": "pull"})
        self._rx.inc(len(payload))
        vec = wire.decode_array(reply["array"], payload)
        return reply["version"], vec

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        meta, payload = wire.encode_array(
            np.asarray(delta, np.float32), self._codec)
        with self._lock:
            reply, buf, sent = wire.request(
                self._sock,
                {"op": "push", "base_version": int(base_version),
                 "array": meta}, payload)
        self._tx.inc(sent)
        params = wire.decode_array(reply["array"], buf)
        return PushResult(accepted=reply["accepted"],
                          version=reply["version"],
                          staleness=reply["staleness"],
                          weight=reply["weight"], params=params)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # lint: swallowed-exception-ok (best-effort close on teardown)
            pass


class ParameterServerTcpFrontend:
    """Serves one `ParameterServer` to TCP workers: accept loop + one thread
    per connection, framed request/reply. Beats the watchdog from the server
    loop and leaves flight-recorder breadcrumbs so a wedged worker fleet is
    diagnosable post-mortem."""

    def __init__(self, server: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0):
        self._server = server
        self._host, self._port = host, port
        self._lsock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns: list = []
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "ParameterServerTcpFrontend":
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((self._host, self._port))
        self._lsock.listen(64)
        self._lsock.settimeout(0.2)
        self._port = self._lsock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="ps-tcp-accept")
        t.start()
        self._threads.append(t)
        _flight_recorder().record("ps_server_start", port=self._port)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            _wd_beat()
            try:
                conn, peer = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us during stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, daemon=True,
                                 args=(conn, peer), name="ps-tcp-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    header, payload = wire.recv_frame(conn)
                except (ConnectionError, OSError):
                    return  # worker hung up (normal end of its run)
                try:
                    reply, buf = self._handle(header, payload)
                except Exception as e:
                    _flight_recorder().record("ps_server_error",
                                              peer=str(peer), error=repr(e))
                    try:
                        wire.send_frame(conn, {"error": repr(e)})
                    except OSError:  # lint: swallowed-exception-ok (peer already gone; error recorded above)
                        pass
                    return
                _wd_beat(self._server.version)
                try:
                    wire.send_frame(conn, reply, buf)
                except (ConnectionError, OSError):
                    return  # worker died mid-reply; its stats are lost only

    def _handle(self, header: dict, payload: bytes):
        op = header.get("op")
        if op == "pull":
            version, vec = self._server.pull_flat()
            meta, buf = wire.encode_array(vec, "none")
            return {"version": version, "array": meta}, buf
        if op == "push":
            delta = wire.decode_array(header["array"], payload)
            res = self._server.push_delta(delta, header["base_version"])
            meta, buf = wire.encode_array(res.params, "none")
            return {"accepted": res.accepted, "version": res.version,
                    "staleness": res.staleness, "weight": res.weight,
                    "array": meta}, buf
        raise ValueError(f"unknown PS op {op!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            self._lsock.close()
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:  # lint: swallowed-exception-ok (already closed by handler thread)
                    pass
        for t in self._threads:
            t.join(timeout=5)
        _flight_recorder().record("ps_server_stop", port=self._port,
                                  version=self._server.version,
                                  pushes=self._server.pushes,
                                  rejected=self._server.rejected)
