"""Parameter-server transports: one API, three interchangeable backends.

The worker loop (param_server.run_worker_loop) only sees ``pull()`` and
``push(delta, base_version)``:

* ``InprocTransport`` — direct method calls into a shared `ParameterServer`
  (worker threads; deterministic, zero-copy; what the unit tests use).
* ``TcpTransport`` + ``ParameterServerTcpFrontend`` — stdlib sockets with
  length-prefixed framed messages (streaming/wire.py), workers in separate
  OS processes so the GIL cannot mask the async win. Pushed deltas may ride
  as bf16 (`codec="bf16"`); pull responses and the canonical store stay f32.
* ``ShmTransport`` — the same-host fast path (ISSUE 14): control verbs stay
  on the TCP wire, but tensor bytes live in per-worker double-buffered
  ``multiprocessing.shared_memory`` segments with seqlock-style version
  stamps. Negotiated over the ordinary TCP connection (``shm_open``); when
  the segments cannot be attached (cross-host peer, old server) the
  transport silently degrades to plain TCP frames.

Every segment this process CREATES is registered in a reaper (atexit unlink
+ ``reap_orphans()`` scanning /dev/shm for segments whose creator pid is
dead), so a SIGKILL'd fleet leaks nothing. Workers only ever *attach*.

The reference's Aeron media driver + ParameterServerNode pair maps onto
frontend + server object; replacing UDP with framed loopback TCP keeps the
protocol inspectable with nothing beyond the stdlib.
"""
from __future__ import annotations

import atexit
import itertools
import json
import os
import socket
import struct
import threading
import time
from multiprocessing import shared_memory
from typing import Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import (
    PS_WIRE_BYTES_TOTAL, SHM_BYTES_TOTAL, SHM_REAPED_TOTAL, SHM_SEGMENTS,
)
from deeplearning4j_tpu.observability.tracing import (
    current_span as _current_span,
    parse_traceparent as _parse_traceparent,
    start_span as _start_span,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.parallel.param_server import (
    ParameterServer, PushResult,
)
from deeplearning4j_tpu.streaming import wire

_wire_bytes = _obs_registry().counter(
    PS_WIRE_BYTES_TOTAL, "PS bytes on the wire, by op and codec")

_shm_gauge = _obs_registry().gauge(
    SHM_SEGMENTS, "shared-memory segments currently owned (created, not yet "
                  "unlinked) by this process").labels()
_shm_bytes = _obs_registry().counter(
    SHM_BYTES_TOTAL, "tensor bytes staged through shared-memory segments, "
                     "by direction")
_shm_reaped = _obs_registry().counter(
    SHM_REAPED_TOTAL, "orphaned dl4j shared-memory segments unlinked by "
                      "reap_orphans (creator pid dead)").labels()


# --------------------------------------------------------------------------
# shared-memory segments: creation registry + reaper

#: every segment name starts with this prefix followed by the CREATOR pid —
#: reap_orphans() uses the pid to decide a segment is garbage
_SHM_PREFIX = "dl4j_shm_"

_shm_lock = threading.Lock()
_shm_created: Dict[str, shared_memory.SharedMemory] = {}
_shm_counter = itertools.count()


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # alive, someone else's
    return True


def create_segment(nbytes: int, kind: str) -> shared_memory.SharedMemory:
    """Create an owned segment named ``dl4j_shm_<pid>_<n>_<kind>`` and
    register it for atexit unlink + orphan reaping."""
    name = f"{_SHM_PREFIX}{os.getpid()}_{next(_shm_counter)}_{kind}"
    shm = shared_memory.SharedMemory(name=name, create=True, size=nbytes)
    with _shm_lock:
        _shm_created[shm.name] = shm
        _shm_gauge.set(len(_shm_created))
    return shm


def attach_segment(name: str) -> shared_memory.SharedMemory:
    """Attach a peer's segment WITHOUT adopting it: Python 3.10's
    resource_tracker registers every attach and would unlink the creator's
    segment when this process exits — unregister immediately so ownership
    stays with the creator (the reaper covers the crash cases)."""
    shm = shared_memory.SharedMemory(name=name)
    with _shm_lock:
        own = name in _shm_created
    if not own:  # same-process attach must keep the creator's registration
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(getattr(shm, "_name", "/" + name),
                                        "shared_memory")
        except Exception:  # lint: swallowed-exception-ok (tracker internals vary by version; worst case is a benign warning at exit)
            pass
    return shm


def release_segment(shm: shared_memory.SharedMemory,
                    unlink: bool = False) -> None:
    """Close (and for the owner: unlink) one segment. A BufferError on
    close means a decoded view is still alive somewhere — the mapping is
    dropped at GC/exit; the unlink (the part that prevents a leak) still
    happens."""
    try:
        shm.close()
    except BufferError:  # lint: swallowed-exception-ok (exported views pin the mmap; unlink below still removes the name)
        pass
    if unlink:
        try:
            shm.unlink()
        except FileNotFoundError:  # lint: swallowed-exception-ok (already reaped by a peer or an earlier pass)
            pass
        with _shm_lock:
            _shm_created.pop(shm.name, None)
            _shm_gauge.set(len(_shm_created))


def release_segment_by_name(name: str) -> bool:
    """Unlink a segment this process created earlier (shard shipping hands
    names, not handles, across the spawn boundary)."""
    with _shm_lock:
        shm = _shm_created.get(name)
    if shm is None:
        return False
    release_segment(shm, unlink=True)
    return True


def _atexit_unlink_all() -> None:
    with _shm_lock:
        segs = list(_shm_created.values())
    for shm in segs:
        release_segment(shm, unlink=True)


atexit.register(_atexit_unlink_all)


def reap_orphans(shm_dir: str = "/dev/shm") -> int:
    """Unlink every ``dl4j_shm_<pid>_*`` segment whose creator pid is dead
    (SIGKILL skips atexit; the NEXT coordinator to start sweeps the corpse).
    Returns the number reaped. No-op on hosts without a /dev/shm."""
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0
    reaped = 0
    for name in names:
        if not name.startswith(_SHM_PREFIX):
            continue
        try:
            pid = int(name[len(_SHM_PREFIX):].split("_", 1)[0])
        except ValueError:
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(shm_dir, name))
        except OSError:
            continue
        reaped += 1
    if reaped:
        _shm_reaped.inc(reaped)
        _flight_recorder().record("shm_reaped", count=reaped)
    return reaped


# --------------------------------------------------------------------------
# seqlock double buffer: the tensor lane of the shm transport

class ShmRing:
    """Two slots in one segment, each ``[seq, version, nbytes | data]``.

    Single-writer seqlock protocol: the writer alternates slots, bumps the
    slot's seq to ODD before touching data, writes, then publishes the even
    seq + version + nbytes. A reader hands back a view ONLY when the stored
    seq is even and matches the seq the control message promised — a torn
    or stale slot raises instead of returning garbage. The control RPC that
    carries (slot, seq) already sequences both sides, so the stamps are the
    integrity check, not the synchronization.
    """

    SLOT_HDR = struct.Struct("!QQQ")  # seq, version, payload nbytes

    def __init__(self, shm: shared_memory.SharedMemory, capacity: int,
                 direction: str = "push"):
        self.shm = shm
        self.capacity = int(capacity)
        self._next = 0
        self._bytes = _shm_bytes.labels(direction=direction)

    @classmethod
    def segment_size(cls, capacity: int) -> int:
        return 2 * (cls.SLOT_HDR.size + int(capacity))

    def _base(self, slot: int) -> int:
        return slot * (self.SLOT_HDR.size + self.capacity)

    def write(self, view, version: int) -> Tuple[int, int]:
        """Copy ``view`` (a byte view) into the next slot; returns
        (slot, seq) for the control message. The one memcpy here IS the
        transfer — nothing else touches these bytes."""
        nbytes = view.nbytes if isinstance(view, memoryview) else len(view)
        if nbytes > self.capacity:
            raise ValueError(f"shm slot overflow: {nbytes} > "
                             f"capacity {self.capacity}")
        slot = self._next
        self._next ^= 1
        base = self._base(slot)
        buf = self.shm.buf
        seq = self.SLOT_HDR.unpack_from(buf, base)[0]
        self.SLOT_HDR.pack_into(buf, base, seq + 1, int(version), nbytes)
        data = base + self.SLOT_HDR.size
        buf[data:data + nbytes] = view
        self.SLOT_HDR.pack_into(buf, base, seq + 2, int(version), nbytes)
        self._bytes.inc(nbytes)
        return slot, seq + 2

    def read(self, slot: int, seq: int) -> Tuple[int, memoryview]:
        """-> (version, data view). The view aliases the slot: consume it
        (or copy) before the writer's NEXT write to this slot."""
        base = self._base(int(slot))
        got, version, nbytes = self.SLOT_HDR.unpack_from(self.shm.buf, base)
        if got != seq or got % 2:
            raise ConnectionError(
                f"shm seqlock mismatch: slot {slot} has seq {got}, control "
                f"message promised {seq}" + (" (torn write)" if got % 2
                                             else ""))
        data = base + self.SLOT_HDR.size
        return version, self.shm.buf[data:data + nbytes]


class TransportError(OSError):
    """The PS is unreachable after the transport's full retry budget. The
    worker must treat this as its own eviction signal: stop training, clean
    up, exit — the membership lease will lapse server-side regardless."""


class Transport:
    """What a PS worker holds: pull the versioned global params, push a
    delta against the version it pulled. The membership verbs
    (register/heartbeat/deregister) ride the same seam so liveness and
    pushes share one failure domain."""

    def pull(self) -> Tuple[int, np.ndarray]:
        raise NotImplementedError

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        raise NotImplementedError

    # ------------------------------------------------- membership (elastic)
    def bind_member(self, member: int, epoch: int) -> None:
        """Attach a (member, epoch) identity: subsequent pushes carry it and
        the server fences them against the membership oracle's leases."""
        self._member, self._epoch = int(member), int(epoch)

    @property
    def member_identity(self) -> Optional[Tuple[int, int]]:
        member = getattr(self, "_member", None)
        return None if member is None else (member, self._epoch)

    def register(self, shard: int, worker: str = "") -> dict:
        raise NotImplementedError

    def heartbeat(self) -> bool:
        raise NotImplementedError

    def deregister(self, reason: str = "done") -> bool:
        raise NotImplementedError

    # ----------------------------------------------------- tracing (fleet)
    def bind_trace_parent(self, ref) -> None:
        """Attach the SpanRef subsequent pushes/pulls parent under when no
        ambient span is set — the worker run loop binds the consume span of
        the batch it is training on, so the push stitches into the
        producer's trace (None clears)."""
        self._trace_ref = ref

    @property
    def trace_parent(self):
        return getattr(self, "_trace_ref", None)

    def _traced(self, name: str, header: dict, **attrs):
        """Open a span for one RPC and stamp its ``traceparent`` onto the
        frame header; the server side parents its handling span from the
        header, which is how one trace id crosses the process boundary.
        Parentless RPCs (the background puller, the heartbeat) carry no
        header and open no span — they would only mint root-trace noise.
        Returns the span (possibly the no-op); caller finishes it."""
        parent = _current_span() or self.trace_parent
        if parent is None:
            from deeplearning4j_tpu.observability.tracing import NOOP_SPAN
            return NOOP_SPAN
        sp = _start_span(name, parent=parent, **attrs)
        tp = sp.traceparent()
        if tp:
            header["traceparent"] = tp
        return sp

    # --------------------------------------------- federation (fleet obs)
    def push_metrics(self, snapshot: dict, *, seq: int, name: str = "",
                     role: str = "worker", events=(), traces=(),
                     final: bool = False) -> Optional[dict]:
        """Ship one cumulative metrics/events/traces frame to the
        coordinator's FederatedRegistry; returns its ``{"accepted",
        "fenced"}`` reply, or None when this transport (or the peer) has no
        federation — publishing degrades to a no-op, never an error."""
        return None

    def push_trace(self, records) -> Optional[dict]:
        """Ship finalized trace records alone (the metrics frame normally
        carries them; this is the standalone hook for tooling)."""
        return None

    def dump_fleet(self, reason: str = "api",
                   force: bool = False) -> Optional[str]:
        """Ask the coordinator to write a fleet flight bundle; returns its
        path (None when unsupported or rate-limited)."""
        return None

    def close(self) -> None:
        pass


class InprocTransport(Transport):
    def __init__(self, server: ParameterServer, federation=None,
                 collector=None):
        self._server = server
        self.federation = federation
        self.collector = collector

    def pull(self) -> Tuple[int, np.ndarray]:
        return self._server.pull_flat()

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        sp = self._traced("ps.push", {}, transport="inproc")
        try:
            ident = self.member_identity
            if ident is None:
                res = self._server.push_delta(delta, base_version)
            else:
                res = self._server.push_delta(delta, base_version,
                                              member=ident[0],
                                              epoch=ident[1])
            sp.set_attr(accepted=res.accepted, version=res.version)
            return res
        finally:
            sp.finish()

    def push_metrics(self, snapshot: dict, *, seq: int, name: str = "",
                     role: str = "worker", events=(), traces=(),
                     final: bool = False) -> Optional[dict]:
        if self.federation is None:
            return None
        ident = self.member_identity
        return self.federation.ingest(
            name=name, epoch=ident[1] if ident else 0,
            member=ident[0] if ident else None, role=role, seq=seq,
            snapshot=snapshot, events=events, traces=traces, final=final)

    def push_trace(self, records) -> Optional[dict]:
        if self.federation is None:
            return None
        self.federation.ingest_traces(records)
        return {"ok": True}

    def dump_fleet(self, reason: str = "api",
                   force: bool = False) -> Optional[str]:
        if self.collector is None:
            return None
        return self.collector.dump(reason=reason, force=force)

    def _membership(self):
        oracle = self._server.membership
        if oracle is None:
            raise RuntimeError("ParameterServer has no membership oracle")
        return oracle

    def register(self, shard: int, worker: str = "") -> dict:
        lease = self._membership().register(shard, worker=worker)
        return {"member": lease.member, "epoch": lease.epoch,
                "lease_s": self._membership().lease_timeout_s}

    def heartbeat(self) -> bool:
        ident = self.member_identity
        return (ident is not None
                and self._membership().heartbeat(ident[0], ident[1]))

    def deregister(self, reason: str = "done") -> bool:
        ident = self.member_identity
        return (ident is not None
                and self._membership().deregister(ident[0], ident[1],
                                                  reason=reason))


class TcpTransport(Transport):
    """Client side of the framed loopback protocol. NOT thread-safe: each
    worker (and its background puller / heartbeat thread) opens its own
    connection via ``clone()``.

    Connects lazily and survives a flaky server: every RPC gets a connect
    timeout, a read timeout, and a bounded exponential-backoff retry budget
    (a dead PS used to hang the worker forever on a blocking recv). When
    the budget is spent the RPC raises ``TransportError``. A retried push
    is at-least-once — the reply may be lost after the delta applied — which
    the staleness-weighted server absorbs the same way it absorbs any
    duplicate delta."""

    def __init__(self, addr: Tuple[str, int], codec: str = "none",
                 timeout: float = 60.0, connect_timeout: float = 5.0,
                 retries: int = 3, backoff_s: float = 0.1,
                 backoff_cap_s: float = 2.0):
        self._addr = tuple(addr)
        self._codec = codec
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        self._retries = max(0, int(retries))
        self._backoff_s = backoff_s
        self._backoff_cap_s = backoff_cap_s
        # reentrant: ShmTransport's fallback calls super().pull()/push()
        # while already holding the lock
        self._lock = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._tx = _wire_bytes.labels(op="push", codec=codec)
        self._rx = _wire_bytes.labels(op="pull", codec="none")

    def clone(self) -> "TcpTransport":
        t = type(self)(self._addr, self._codec, self._timeout,
                       self._connect_timeout, self._retries,
                       self._backoff_s, self._backoff_cap_s)
        ident = self.member_identity
        if ident is not None:
            t.bind_member(*ident)
        return t

    # ------------------------------------------------------------- plumbing
    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # lint: swallowed-exception-ok (socket already dead is why we drop it)
                pass
            self._sock = None

    def _rpc(self, header: dict, payload: bytes = b""):
        """One request/reply with reconnect + bounded exponential backoff.
        Caller holds self._lock. RuntimeError (a server-side error reply)
        propagates immediately: the server is alive, retrying is wrong."""
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            if attempt:
                delay = min(self._backoff_s * (2 ** (attempt - 1)),
                            self._backoff_cap_s)
                time.sleep(delay)
            try:
                if self._sock is None:
                    self._sock = wire.connect(
                        self._addr, timeout=self._connect_timeout)
                    self._sock.settimeout(self._timeout)
                return wire.request(self._sock, header, payload)
            except RuntimeError:
                raise
            except (socket.timeout, ConnectionError, OSError) as e:
                last = e
                self._drop_sock()
        raise TransportError(
            f"PS at {self._addr} unreachable after {self._retries + 1} "
            f"attempts (op={header.get('op')!r}): {last!r}") from last

    # ------------------------------------------------------------- core API
    def pull(self) -> Tuple[int, np.ndarray]:
        header = {"op": "pull"}
        sp = self._traced("ps.pull", header, transport="tcp")
        try:
            with self._lock:
                # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
                reply, payload, _ = self._rpc(header)
            self._rx.inc(len(payload))
            vec = wire.decode_array(reply["array"], payload)
            sp.set_attr(version=reply["version"])
            return reply["version"], vec
        finally:
            sp.finish()

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        meta, payload = wire.encode_array(
            np.asarray(delta, np.float32), self._codec)
        header = {"op": "push", "base_version": int(base_version),
                  "array": meta}
        ident = self.member_identity
        if ident is not None:
            header["member"], header["epoch"] = ident
        sp = self._traced("ps.push", header, transport="tcp",
                          base_version=int(base_version))
        try:
            with self._lock:
                # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
                reply, buf, sent = self._rpc(header, payload)
            self._tx.inc(sent)
            params = wire.decode_array(reply["array"], buf)
            sp.set_attr(accepted=reply["accepted"],
                        version=reply["version"])
            return PushResult(accepted=reply["accepted"],
                              version=reply["version"],
                              staleness=reply["staleness"],
                              weight=reply["weight"], params=params,
                              fenced=reply.get("fenced", False))
        finally:
            sp.finish()

    # ------------------------------------------------- membership (elastic)
    def register(self, shard: int, worker: str = "") -> dict:
        with self._lock:
            # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
            reply, _, _ = self._rpc(
                {"op": "register", "shard": int(shard), "worker": worker})
        return reply

    def heartbeat(self) -> bool:
        ident = self.member_identity
        if ident is None:
            return False
        with self._lock:
            # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
            reply, _, _ = self._rpc(
                {"op": "heartbeat", "member": ident[0], "epoch": ident[1]})
        return bool(reply.get("ok"))

    def deregister(self, reason: str = "done") -> bool:
        ident = self.member_identity
        if ident is None:
            return False
        with self._lock:
            # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
            reply, _, _ = self._rpc(
                {"op": "deregister", "member": ident[0],
                 "epoch": ident[1], "reason": reason})
        return bool(reply.get("ok"))

    # --------------------------------------------- federation (fleet obs)
    def push_metrics(self, snapshot: dict, *, seq: int, name: str = "",
                     role: str = "worker", events=(), traces=(),
                     final: bool = False) -> Optional[dict]:
        if getattr(self, "_fed_refused", False):
            return None
        header = {"op": "metrics_push", "seq": int(seq), "name": name,
                  "role": role, "final": bool(final)}
        ident = self.member_identity
        if ident is not None:
            header["member"], header["epoch"] = ident
        payload = json.dumps(
            {"snapshot": snapshot, "events": list(events),
             "traces": list(traces)},
            separators=(",", ":"), default=repr).encode("utf-8")
        try:
            with self._lock:
                # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
                reply, _, sent = self._rpc(header, payload)
        except RuntimeError:
            # pre-federation coordinator ("unknown PS op") or one started
            # without a federation: stop asking, publishing is optional
            self._fed_refused = True
            return None
        self._tx.inc(sent)
        return reply

    def push_trace(self, records) -> Optional[dict]:
        if getattr(self, "_fed_refused", False):
            return None
        payload = json.dumps(list(records), separators=(",", ":"),
                             default=repr).encode("utf-8")
        try:
            with self._lock:
                # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
                reply, _, _ = self._rpc({"op": "trace_push"}, payload)
        except RuntimeError:
            self._fed_refused = True
            return None
        return reply

    def dump_fleet(self, reason: str = "api",
                   force: bool = False) -> Optional[str]:
        try:
            with self._lock:
                # lint: blocking-under-lock-ok (the transport lock IS the RPC serializer: one in-flight request per connection, and reconnect backoff must hold it)
                reply, _, _ = self._rpc({"op": "dump_fleet",
                                         "reason": reason,
                                         "force": bool(force)})
        except RuntimeError:
            return None
        return reply.get("path")

    def close(self) -> None:
        with self._lock:
            self._drop_sock()


class ShmTransport(TcpTransport):
    """Same-host fast path: tensor bytes ride per-worker shared-memory
    rings, only control verbs (slot, seq, version, array meta) cross the
    socket.

    Negotiation happens over the ordinary TCP connection: the first
    pull/push issues ``shm_open``; the server creates a (push ring, pull
    ring) pair sized to the flat parameter vector, keyed by a session token
    (NOT the connection — the inherited reconnect/retry machinery keeps
    working across a dropped socket). If the open is refused (old server)
    or the segments can't be attached (cross-host peer), the transport
    records a flight breadcrumb and permanently degrades to the inherited
    plain-TCP frames — same API, same results, just slower.

    The client COPIES params out of the pull ring before returning: the
    slot is reused two pulls later, while run_worker_loop still holds the
    vector. That one copy replaces the socket read; the push direction is
    fully zero-copy (the server consumes the delta view under its own lock
    before replying)."""

    def __init__(self, addr: Tuple[str, int], codec: str = "none",
                 timeout: float = 60.0, connect_timeout: float = 5.0,
                 retries: int = 3, backoff_s: float = 0.1,
                 backoff_cap_s: float = 2.0):
        super().__init__(addr, codec, timeout, connect_timeout, retries,
                         backoff_s, backoff_cap_s)
        self._token: Optional[str] = None
        self._push_ring: Optional[ShmRing] = None
        self._pull_ring: Optional[ShmRing] = None
        self._shm_ok: Optional[bool] = None  # None = not yet negotiated

    # ------------------------------------------------------------ negotiate
    def _negotiate(self) -> bool:
        """Caller holds self._lock. One attempt per transport lifetime:
        either the rings attach or we are a TcpTransport from now on."""
        if self._shm_ok is not None:
            return self._shm_ok
        push_seg = pull_seg = None
        try:
            reply, _, _ = self._rpc({"op": "shm_open", "pid": os.getpid()})
            if not reply.get("ok"):
                raise OSError(reply.get("error", "shm_open refused"))
            push_seg = attach_segment(reply["push"])
            pull_seg = attach_segment(reply["pull"])
            cap = int(reply["capacity"])
            self._push_ring = ShmRing(push_seg, cap, direction="push")
            self._pull_ring = ShmRing(pull_seg, cap, direction="pull")
            self._token = reply["token"]
            self._shm_ok = True
        except (RuntimeError, OSError, KeyError, ValueError) as e:
            # RuntimeError = pre-shm server's "unknown PS op" error reply;
            # OSError = segments not attachable (cross-host). Either way:
            # the negotiated fallback IS the inherited TCP path.
            for seg in (push_seg, pull_seg):
                if seg is not None:
                    release_segment(seg)
            self._push_ring = self._pull_ring = None
            self._shm_ok = False
            _flight_recorder().record("ps_shm_fallback",
                                      addr=str(self._addr), error=repr(e))
        return self._shm_ok

    @property
    def shm_active(self) -> Optional[bool]:
        return self._shm_ok

    # ------------------------------------------------------------- core API
    def pull(self) -> Tuple[int, np.ndarray]:
        if self._shm_ok is False:
            return super().pull()
        header = {"op": "pull_shm"}
        sp = self._traced("ps.pull", header, transport="shm")
        try:
            with self._lock:
                if not self._negotiate():
                    return super().pull()
                header["token"] = self._token
                reply, _, _ = self._rpc(header)
                _, view = self._pull_ring.read(reply["slot"], reply["seq"])
                vec = np.frombuffer(view, dtype=np.float32).copy()  # lint: hot-path-copy-ok (slot is reused two pulls later while the worker still holds this vec)
            sp.set_attr(version=reply["version"])
            return reply["version"], vec
        finally:
            sp.finish()

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        if self._shm_ok is False:
            return super().push(delta, base_version)
        meta, payload = wire.encode_array(
            np.asarray(delta, np.float32), self._codec)
        header = {"op": "push_shm", "base_version": int(base_version),
                  "array": meta}
        ident = self.member_identity
        if ident is not None:
            header["member"], header["epoch"] = ident
        sp = self._traced("ps.push", header, transport="shm",
                          base_version=int(base_version))
        try:
            with self._lock:
                if not self._negotiate():
                    return super().push(delta, base_version)
                header["token"] = self._token
                header["slot"], header["seq"] = self._push_ring.write(
                    payload, int(base_version))
                reply, _, _ = self._rpc(header)
                _, pview = self._pull_ring.read(reply["pslot"],
                                                reply["pseq"])
                params = np.frombuffer(pview, dtype=np.float32).copy()  # lint: hot-path-copy-ok (same slot-reuse hazard as pull)
            sp.set_attr(accepted=reply["accepted"],
                        version=reply["version"])
            return PushResult(accepted=reply["accepted"],
                              version=reply["version"],
                              staleness=reply["staleness"],
                              weight=reply["weight"], params=params,
                              fenced=reply.get("fenced", False))
        finally:
            sp.finish()

    def close(self) -> None:
        with self._lock:
            for ring in (self._push_ring, self._pull_ring):
                if ring is not None:
                    release_segment(ring.shm)  # attach-side: close only
            self._push_ring = self._pull_ring = None
            self._shm_ok = None
            self._token = None
            self._drop_sock()


# --------------------------------------------------------------------------
# shard shipping: (x, y) batches through one segment instead of an npz
# tempfile — no compression, no filesystem round-trip; the coordinator owns
# (and unlinks) the segment, workers attach read-only.

def write_shard_segment(arrays: Dict[str, np.ndarray], kind: str = "shard",
                        ) -> str:
    """Pack named arrays into a fresh owned segment:
    ``!Q json_len | json metas | concatenated array bytes``. Returns the
    segment name (ship it as ``shm://<name>``)."""
    metas, views = wire.pack_arrays(arrays)
    hdr = json.dumps(metas, separators=(",", ":")).encode("utf-8")
    total = 8 + len(hdr) + sum(v.nbytes for v in views)
    seg = create_segment(total, kind)
    buf = seg.buf
    struct.pack_into("!Q", buf, 0, len(hdr))
    buf[8:8 + len(hdr)] = hdr
    off = 8 + len(hdr)
    for v in views:
        buf[off:off + v.nbytes] = v
        off += v.nbytes
    _shm_bytes.labels(direction="shard").inc(total)
    return seg.name


def read_shard_segment(name: str) -> Dict[str, np.ndarray]:
    """Attach + decode a shard segment. The returned arrays OWN their data
    (the segment may be unlinked by the coordinator as soon as the worker
    starts training), so this materializes — that is the batch load, not
    the push hot path."""
    shm = attach_segment(name)
    try:
        (hdr_len,) = struct.unpack_from("!Q", shm.buf, 0)
        metas = json.loads(bytes(shm.buf[8:8 + hdr_len]).decode("utf-8"))
        body = shm.buf[8 + hdr_len:]
        out = {k: np.array(v) for k, v in
               wire.unpack_arrays(metas, body).items()}
        del body
    finally:
        release_segment(shm)
    return out


class ParameterServerTcpFrontend:
    """Serves one `ParameterServer` to TCP workers: accept loop + one thread
    per connection, framed request/reply. Beats the watchdog from the server
    loop and leaves flight-recorder breadcrumbs so a wedged worker fleet is
    diagnosable post-mortem."""

    def __init__(self, server: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0, federation=None, collector=None):
        self._server = server
        #: FederatedRegistry / FleetCollector the fleet-observability verbs
        #: (metrics_push / trace_push / dump_fleet) land on; None keeps the
        #: verbs disabled (an error reply, like membership without an oracle)
        self.federation = federation
        self.collector = collector
        self._host, self._port = host, port
        self._lsock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns: list = []
        self._lock = threading.Lock()
        # shm sessions are keyed by token, NOT connection: a client that
        # reconnects mid-run keeps its rings. Sessions die with stop().
        self._shm_sessions: Dict[str, Tuple[ShmRing, ShmRing]] = {}
        self._shm_next = itertools.count(1)

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "ParameterServerTcpFrontend":
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((self._host, self._port))
        self._lsock.listen(64)
        self._lsock.settimeout(0.2)
        self._port = self._lsock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="ps-tcp-accept")
        t.start()
        self._threads.append(t)
        _flight_recorder().record("ps_server_start", port=self._port)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            _wd_beat()
            try:
                conn, peer = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us during stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, daemon=True,
                                 args=(conn, peer), name="ps-tcp-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        # one reusable receive buffer per connection: every op fully
        # consumes its payload inside _handle (the push delta is applied
        # under the server lock before the reply is built), so the next
        # frame may overwrite it
        rbuf = bytearray()
        with conn:
            while not self._stop.is_set():
                try:
                    header, payload = wire.recv_frame(conn, rbuf)
                except (ConnectionError, OSError):
                    return  # worker hung up (normal end of its run)
                try:
                    reply, buf = self._handle(header, payload)
                    payload = None  # drop the view so rbuf can grow in place
                except Exception as e:
                    _flight_recorder().record("ps_server_error",
                                              peer=str(peer), error=repr(e))
                    try:
                        wire.send_frame(conn, {"error": repr(e)})
                    except OSError:  # lint: swallowed-exception-ok (peer already gone; error recorded above)
                        pass
                    return
                _wd_beat(self._server.version)
                try:
                    wire.send_frame(conn, reply, buf)
                except (ConnectionError, OSError):
                    return  # worker died mid-reply; its stats are lost only

    def _apply_span(self, header: dict):
        """Server-side half of the wire-propagated trace: a push/pull frame
        carrying ``traceparent`` gets a coordinator-local ``ps.apply`` span
        parented under the worker's RPC span — it lands in the
        COORDINATOR's TraceStore, stitching the worker's trace id into the
        fleet view even before the worker ships its own fragments."""
        ref = _parse_traceparent(header.get("traceparent"))
        if ref is None:
            from deeplearning4j_tpu.observability.tracing import NOOP_SPAN
            return NOOP_SPAN
        return _start_span("ps.apply", parent=ref,
                           member=header.get("member"),
                           epoch=header.get("epoch"))

    def _handle(self, header: dict, payload: bytes):
        op = header.get("op")
        if op == "pull":
            version, vec = self._server.pull_flat()
            meta, buf = wire.encode_array(vec, "none")
            return {"version": version, "array": meta}, buf
        if op == "push":
            sp = self._apply_span(header)
            try:
                delta = wire.decode_array(header["array"], payload)
                res = self._server.push_delta(
                    delta, header["base_version"],
                    member=header.get("member"), epoch=header.get("epoch"))
                sp.set_attr(accepted=res.accepted, version=res.version,
                            fenced=res.fenced)
            finally:
                sp.finish()
            meta, buf = wire.encode_array(res.params, "none")
            return {"accepted": res.accepted, "version": res.version,
                    "staleness": res.staleness, "weight": res.weight,
                    "fenced": res.fenced, "array": meta}, buf
        if op == "metrics_push":
            fed = self.federation
            if fed is None:
                raise ValueError(
                    "PS op 'metrics_push' requires a federation "
                    "(ParameterServerTcpFrontend(..., federation=...))")
            body = json.loads(bytes(payload).decode("utf-8")) \
                if len(payload) else {}
            res = fed.ingest(
                name=header.get("name", ""),
                epoch=header.get("epoch", 0),
                member=header.get("member"),
                role=header.get("role", "worker"),
                seq=header.get("seq", 0),
                snapshot=body.get("snapshot") or {},
                events=body.get("events") or (),
                traces=body.get("traces") or (),
                final=bool(header.get("final")),
                nbytes=len(payload))
            return res, b""
        if op == "trace_push":
            fed = self.federation
            if fed is None:
                raise ValueError(
                    "PS op 'trace_push' requires a federation "
                    "(ParameterServerTcpFrontend(..., federation=...))")
            records = json.loads(bytes(payload).decode("utf-8")) \
                if len(payload) else []
            fed.ingest_traces(records)
            return {"ok": True, "ingested": len(records)}, b""
        if op == "dump_fleet":
            col = self.collector
            if col is None:
                raise ValueError(
                    "PS op 'dump_fleet' requires a fleet collector "
                    "(ParameterServerTcpFrontend(..., collector=...))")
            path = col.dump(reason=header.get("reason", "api"),
                            force=bool(header.get("force")))
            return {"ok": path is not None, "path": path}, b""
        if op == "register":
            oracle = self._require_membership(op)
            lease = oracle.register(header["shard"],
                                    worker=header.get("worker", ""))
            return {"member": lease.member, "epoch": lease.epoch,
                    "lease_s": oracle.lease_timeout_s}, b""
        if op == "heartbeat":
            oracle = self._require_membership(op)
            ok = oracle.heartbeat(header["member"], header["epoch"])
            return {"ok": ok}, b""
        if op == "deregister":
            oracle = self._require_membership(op)
            ok = oracle.deregister(header["member"], header["epoch"],
                                   reason=header.get("reason", "done"))
            return {"ok": ok}, b""
        if op == "shm_open":
            return self._shm_open(header), b""
        if op == "pull_shm":
            _, pull_ring = self._shm_session(header)
            version, vec = self._server.pull_flat()
            slot, seq = pull_ring.write(wire._byteview(vec), version)
            return {"version": version, "slot": slot, "seq": seq}, b""
        if op == "push_shm":
            push_ring, pull_ring = self._shm_session(header)
            sp = self._apply_span(header)
            try:
                _, dview = push_ring.read(header["slot"], header["seq"])
                # zero-copy: the delta view aliases the client's push slot;
                # it is fully consumed by push_delta (under the server
                # lock) before this reply releases the client to write
                # again
                delta = wire.decode_array(header["array"], dview)
                res = self._server.push_delta(
                    delta, header["base_version"],
                    member=header.get("member"), epoch=header.get("epoch"))
                sp.set_attr(accepted=res.accepted, version=res.version,
                            fenced=res.fenced)
            finally:
                sp.finish()
            pslot, pseq = pull_ring.write(wire._byteview(res.params),
                                          res.version)
            return {"accepted": res.accepted, "version": res.version,
                    "staleness": res.staleness, "weight": res.weight,
                    "fenced": res.fenced, "pslot": pslot, "pseq": pseq}, b""
        raise ValueError(f"unknown PS op {op!r}")

    # -------------------------------------------------------- shm sessions
    def _shm_open(self, header: dict) -> dict:
        reap_orphans()  # every new session sweeps dead fleets' segments
        capacity = self._server.pull_flat()[1].nbytes
        try:
            push_seg = create_segment(ShmRing.segment_size(capacity), "push")
            pull_seg = create_segment(ShmRing.segment_size(capacity), "pull")
        except OSError as e:
            return {"ok": False, "error": repr(e)}
        with self._lock:
            token = f"shm{next(self._shm_next)}"
            self._shm_sessions[token] = (
                ShmRing(push_seg, capacity, direction="push"),
                ShmRing(pull_seg, capacity, direction="pull"))
        _flight_recorder().record("ps_shm_open", token=token,
                                  pid=header.get("pid"), capacity=capacity)
        return {"ok": True, "token": token, "push": push_seg.name,
                "pull": pull_seg.name, "capacity": capacity}

    def _shm_session(self, header: dict) -> Tuple[ShmRing, ShmRing]:
        with self._lock:
            sess = self._shm_sessions.get(header.get("token"))
        if sess is None:
            raise ValueError(f"unknown shm token {header.get('token')!r} "
                             "(server restarted? reopen the session)")
        return sess

    def _require_membership(self, op: str):
        oracle = getattr(self._server, "membership", None)
        if oracle is None:
            raise ValueError(
                f"PS op {op!r} requires a membership oracle "
                "(ParameterServer(..., membership=MembershipOracle()))")
        return oracle

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            self._lsock.close()
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:  # lint: swallowed-exception-ok (already closed by handler thread)
                    pass
        for t in self._threads:
            t.join(timeout=5)
        with self._lock:
            sessions, self._shm_sessions = self._shm_sessions, {}
        for push_ring, pull_ring in sessions.values():
            release_segment(push_ring.shm, unlink=True)
            release_segment(pull_ring.shm, unlink=True)
        _flight_recorder().record("ps_server_stop", port=self._port,
                                  version=self._server.version,
                                  pushes=self._server.pushes,
                                  rejected=self._server.rejected)
