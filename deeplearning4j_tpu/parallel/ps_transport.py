"""Parameter-server transports: one API, two interchangeable backends.

The worker loop (param_server.run_worker_loop) only sees ``pull()`` and
``push(delta, base_version)``:

* ``InprocTransport`` — direct method calls into a shared `ParameterServer`
  (worker threads; deterministic, zero-copy; what the unit tests use).
* ``TcpTransport`` + ``ParameterServerTcpFrontend`` — stdlib sockets with
  length-prefixed framed messages (streaming/wire.py), workers in separate
  OS processes so the GIL cannot mask the async win. Pushed deltas may ride
  as bf16 (`codec="bf16"`); pull responses and the canonical store stay f32.

The reference's Aeron media driver + ParameterServerNode pair maps onto
frontend + server object; replacing UDP with framed loopback TCP keeps the
protocol inspectable with nothing beyond the stdlib.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.observability.flight_recorder import (
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import PS_WIRE_BYTES_TOTAL
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.parallel.param_server import (
    ParameterServer, PushResult,
)
from deeplearning4j_tpu.streaming import wire

_wire_bytes = _obs_registry().counter(
    PS_WIRE_BYTES_TOTAL, "PS bytes on the wire, by op and codec")


class TransportError(OSError):
    """The PS is unreachable after the transport's full retry budget. The
    worker must treat this as its own eviction signal: stop training, clean
    up, exit — the membership lease will lapse server-side regardless."""


class Transport:
    """What a PS worker holds: pull the versioned global params, push a
    delta against the version it pulled. The membership verbs
    (register/heartbeat/deregister) ride the same seam so liveness and
    pushes share one failure domain."""

    def pull(self) -> Tuple[int, np.ndarray]:
        raise NotImplementedError

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        raise NotImplementedError

    # ------------------------------------------------- membership (elastic)
    def bind_member(self, member: int, epoch: int) -> None:
        """Attach a (member, epoch) identity: subsequent pushes carry it and
        the server fences them against the membership oracle's leases."""
        self._member, self._epoch = int(member), int(epoch)

    @property
    def member_identity(self) -> Optional[Tuple[int, int]]:
        member = getattr(self, "_member", None)
        return None if member is None else (member, self._epoch)

    def register(self, shard: int, worker: str = "") -> dict:
        raise NotImplementedError

    def heartbeat(self) -> bool:
        raise NotImplementedError

    def deregister(self, reason: str = "done") -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InprocTransport(Transport):
    def __init__(self, server: ParameterServer):
        self._server = server

    def pull(self) -> Tuple[int, np.ndarray]:
        return self._server.pull_flat()

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        ident = self.member_identity
        if ident is None:
            return self._server.push_delta(delta, base_version)
        return self._server.push_delta(delta, base_version,
                                       member=ident[0], epoch=ident[1])

    def _membership(self):
        oracle = self._server.membership
        if oracle is None:
            raise RuntimeError("ParameterServer has no membership oracle")
        return oracle

    def register(self, shard: int, worker: str = "") -> dict:
        lease = self._membership().register(shard, worker=worker)
        return {"member": lease.member, "epoch": lease.epoch,
                "lease_s": self._membership().lease_timeout_s}

    def heartbeat(self) -> bool:
        ident = self.member_identity
        return (ident is not None
                and self._membership().heartbeat(ident[0], ident[1]))

    def deregister(self, reason: str = "done") -> bool:
        ident = self.member_identity
        return (ident is not None
                and self._membership().deregister(ident[0], ident[1],
                                                  reason=reason))


class TcpTransport(Transport):
    """Client side of the framed loopback protocol. NOT thread-safe: each
    worker (and its background puller / heartbeat thread) opens its own
    connection via ``clone()``.

    Connects lazily and survives a flaky server: every RPC gets a connect
    timeout, a read timeout, and a bounded exponential-backoff retry budget
    (a dead PS used to hang the worker forever on a blocking recv). When
    the budget is spent the RPC raises ``TransportError``. A retried push
    is at-least-once — the reply may be lost after the delta applied — which
    the staleness-weighted server absorbs the same way it absorbs any
    duplicate delta."""

    def __init__(self, addr: Tuple[str, int], codec: str = "none",
                 timeout: float = 60.0, connect_timeout: float = 5.0,
                 retries: int = 3, backoff_s: float = 0.1,
                 backoff_cap_s: float = 2.0):
        self._addr = tuple(addr)
        self._codec = codec
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        self._retries = max(0, int(retries))
        self._backoff_s = backoff_s
        self._backoff_cap_s = backoff_cap_s
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._tx = _wire_bytes.labels(op="push", codec=codec)
        self._rx = _wire_bytes.labels(op="pull", codec="none")

    def clone(self) -> "TcpTransport":
        t = TcpTransport(self._addr, self._codec, self._timeout,
                         self._connect_timeout, self._retries,
                         self._backoff_s, self._backoff_cap_s)
        ident = self.member_identity
        if ident is not None:
            t.bind_member(*ident)
        return t

    # ------------------------------------------------------------- plumbing
    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:  # lint: swallowed-exception-ok (socket already dead is why we drop it)
                pass
            self._sock = None

    def _rpc(self, header: dict, payload: bytes = b""):
        """One request/reply with reconnect + bounded exponential backoff.
        Caller holds self._lock. RuntimeError (a server-side error reply)
        propagates immediately: the server is alive, retrying is wrong."""
        last: Optional[BaseException] = None
        for attempt in range(self._retries + 1):
            if attempt:
                delay = min(self._backoff_s * (2 ** (attempt - 1)),
                            self._backoff_cap_s)
                time.sleep(delay)
            try:
                if self._sock is None:
                    self._sock = wire.connect(
                        self._addr, timeout=self._connect_timeout)
                    self._sock.settimeout(self._timeout)
                return wire.request(self._sock, header, payload)
            except RuntimeError:
                raise
            except (socket.timeout, ConnectionError, OSError) as e:
                last = e
                self._drop_sock()
        raise TransportError(
            f"PS at {self._addr} unreachable after {self._retries + 1} "
            f"attempts (op={header.get('op')!r}): {last!r}") from last

    # ------------------------------------------------------------- core API
    def pull(self) -> Tuple[int, np.ndarray]:
        with self._lock:
            reply, payload, _ = self._rpc({"op": "pull"})
        self._rx.inc(len(payload))
        vec = wire.decode_array(reply["array"], payload)
        return reply["version"], vec

    def push(self, delta: np.ndarray, base_version: int) -> PushResult:
        meta, payload = wire.encode_array(
            np.asarray(delta, np.float32), self._codec)
        header = {"op": "push", "base_version": int(base_version),
                  "array": meta}
        ident = self.member_identity
        if ident is not None:
            header["member"], header["epoch"] = ident
        with self._lock:
            reply, buf, sent = self._rpc(header, payload)
        self._tx.inc(sent)
        params = wire.decode_array(reply["array"], buf)
        return PushResult(accepted=reply["accepted"],
                          version=reply["version"],
                          staleness=reply["staleness"],
                          weight=reply["weight"], params=params,
                          fenced=reply.get("fenced", False))

    # ------------------------------------------------- membership (elastic)
    def register(self, shard: int, worker: str = "") -> dict:
        with self._lock:
            reply, _, _ = self._rpc(
                {"op": "register", "shard": int(shard), "worker": worker})
        return reply

    def heartbeat(self) -> bool:
        ident = self.member_identity
        if ident is None:
            return False
        with self._lock:
            reply, _, _ = self._rpc(
                {"op": "heartbeat", "member": ident[0], "epoch": ident[1]})
        return bool(reply.get("ok"))

    def deregister(self, reason: str = "done") -> bool:
        ident = self.member_identity
        if ident is None:
            return False
        with self._lock:
            reply, _, _ = self._rpc(
                {"op": "deregister", "member": ident[0],
                 "epoch": ident[1], "reason": reason})
        return bool(reply.get("ok"))

    def close(self) -> None:
        with self._lock:
            self._drop_sock()


class ParameterServerTcpFrontend:
    """Serves one `ParameterServer` to TCP workers: accept loop + one thread
    per connection, framed request/reply. Beats the watchdog from the server
    loop and leaves flight-recorder breadcrumbs so a wedged worker fleet is
    diagnosable post-mortem."""

    def __init__(self, server: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0):
        self._server = server
        self._host, self._port = host, port
        self._lsock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._conns: list = []
        self._lock = threading.Lock()

    @property
    def port(self) -> int:
        return self._port

    def start(self) -> "ParameterServerTcpFrontend":
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((self._host, self._port))
        self._lsock.listen(64)
        self._lsock.settimeout(0.2)
        self._port = self._lsock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="ps-tcp-accept")
        t.start()
        self._threads.append(t)
        _flight_recorder().record("ps_server_start", port=self._port)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            _wd_beat()
            try:
                conn, peer = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed under us during stop()
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            t = threading.Thread(target=self._serve_conn, daemon=True,
                                 args=(conn, peer), name="ps-tcp-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket, peer) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    header, payload = wire.recv_frame(conn)
                except (ConnectionError, OSError):
                    return  # worker hung up (normal end of its run)
                try:
                    reply, buf = self._handle(header, payload)
                except Exception as e:
                    _flight_recorder().record("ps_server_error",
                                              peer=str(peer), error=repr(e))
                    try:
                        wire.send_frame(conn, {"error": repr(e)})
                    except OSError:  # lint: swallowed-exception-ok (peer already gone; error recorded above)
                        pass
                    return
                _wd_beat(self._server.version)
                try:
                    wire.send_frame(conn, reply, buf)
                except (ConnectionError, OSError):
                    return  # worker died mid-reply; its stats are lost only

    def _handle(self, header: dict, payload: bytes):
        op = header.get("op")
        if op == "pull":
            version, vec = self._server.pull_flat()
            meta, buf = wire.encode_array(vec, "none")
            return {"version": version, "array": meta}, buf
        if op == "push":
            delta = wire.decode_array(header["array"], payload)
            res = self._server.push_delta(
                delta, header["base_version"],
                member=header.get("member"), epoch=header.get("epoch"))
            meta, buf = wire.encode_array(res.params, "none")
            return {"accepted": res.accepted, "version": res.version,
                    "staleness": res.staleness, "weight": res.weight,
                    "fenced": res.fenced, "array": meta}, buf
        if op == "register":
            oracle = self._require_membership(op)
            lease = oracle.register(header["shard"],
                                    worker=header.get("worker", ""))
            return {"member": lease.member, "epoch": lease.epoch,
                    "lease_s": oracle.lease_timeout_s}, b""
        if op == "heartbeat":
            oracle = self._require_membership(op)
            ok = oracle.heartbeat(header["member"], header["epoch"])
            return {"ok": ok}, b""
        if op == "deregister":
            oracle = self._require_membership(op)
            ok = oracle.deregister(header["member"], header["epoch"],
                                   reason=header.get("reason", "done"))
            return {"ok": ok}, b""
        raise ValueError(f"unknown PS op {op!r}")

    def _require_membership(self, op: str):
        oracle = getattr(self._server, "membership", None)
        if oracle is None:
            raise ValueError(
                f"PS op {op!r} requires a membership oracle "
                "(ParameterServer(..., membership=MembershipOracle()))")
        return oracle

    def stop(self) -> None:
        self._stop.set()
        if self._lsock is not None:
            self._lsock.close()
        with self._lock:
            for conn in self._conns:
                try:
                    conn.close()
                except OSError:  # lint: swallowed-exception-ok (already closed by handler thread)
                    pass
        for t in self._threads:
            t.join(timeout=5)
        _flight_recorder().record("ps_server_stop", port=self._port,
                                  version=self._server.version,
                                  pushes=self._server.pushes,
                                  rejected=self._server.rejected)
