"""Device-mesh construction and sharding rules.

This is the TPU-native replacement for the reference's entire distribution transport
stack (SURVEY.md §2.4): Spark RDD tree-aggregation, the Aeron parameter server, and
in-process P2P parameter averaging all become XLA collectives over a
``jax.sharding.Mesh`` — psum over ICI inside a slice, DCN across slices via
jax.distributed. Axis conventions:

  data  — data parallelism (ParallelWrapper / ParameterAveragingTrainingMaster)
  model — tensor parallelism (new TPU-native capability, absent in reference)
  seq   — sequence/context parallelism for long sequences (ring attention)

Multi-host: call ``init_distributed()`` (jax.distributed.initialize) before building
the mesh; jax.devices() then spans all hosts and the same code scales out — the
replacement for the reference's Spark cluster setup.
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host init (replaces Spark driver/executor RPC + Aeron media driver,
    reference ParameterServerParallelWrapper.java:159-161)."""
    # CPU cross-process collectives need an explicit implementation: the
    # jax_cpu_collectives_implementation flag defaults to "none" and (in jax
    # 0.4.37) is NOT read from the environment, so a multi-process CPU
    # cluster would form and then fail every collective at compile time with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Must run before the first backend is created; harmless on TPU.
    try:
        from jaxlib.xla_client import _xla
        if hasattr(_xla, "make_gloo_tcp_collectives"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:  # unknown flag / exotic jaxlib: old behavior
        logging.getLogger(__name__).debug(
            "could not enable gloo CPU collectives: %s", e)
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes, process_id=process_id)


def build_mesh(axes: dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh, e.g. build_mesh({"data": 4, "model": 2})."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = list(axes.values())
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"Mesh needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def build_hybrid_mesh(ici_axes: dict[str, int],
                      dcn_axes: dict[str, int]) -> Mesh:
    """Multi-slice mesh: per-axis size = ici_size * dcn_size, with device
    placement chosen so the ``dcn_axes`` multiplier spans slices (DCN) and the
    ``ici_axes`` factor stays inside a slice (ICI).

    This encodes the scaling rule the reference never needed (its Spark tree-
    aggregate treated all links alike, SURVEY.md §2.4): collective-heavy axes
    (tensor/sequence parallel) must ride ICI, so give them dcn multiplier 1;
    bandwidth-light axes (data parallel gradient all-reduce, expert all_to_all
    at low frequency) may span slices. Keys of ``dcn_axes`` must be a subset
    of ``ici_axes`` (missing keys mean multiplier 1).

    On a single slice/process (including the CPU test mesh) this degrades to
    a plain mesh with the same axis names and product sizes, so code written
    against it runs unchanged from one chip to multi-slice.
    """
    dcn = {k: int(dcn_axes.get(k, 1)) for k in ici_axes}
    unknown = set(dcn_axes) - set(ici_axes)
    if unknown:
        raise ValueError(f"dcn_axes {sorted(unknown)} not present in ici_axes "
                         f"{sorted(ici_axes)}")
    n_slices = len({getattr(d, "slice_index", 0) for d in jax.devices()})
    if n_slices > 1 and any(v > 1 for v in dcn.values()):
        # real multi-slice topology: misconfigurations must raise loudly —
        # a silent fallback here could lay a collective-heavy axis across
        # DCN, the exact failure this helper exists to prevent
        from jax.experimental import mesh_utils
        devs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_axes.values()),
            dcn_mesh_shape=tuple(dcn.values()))
        return Mesh(devs, tuple(ici_axes.keys()))
    return build_mesh({k: ici_axes[k] * dcn[k] for k in ici_axes})


def data_parallel_mesh(n: Optional[int] = None,
                       devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = n or len(devices)
    return build_mesh({"data": n}, devices)


# --------------------------------------------------------------------- shardings
# Layout DECISIONS live in parallel/partition.py (the rule engine); these are
# thin delegates kept for API stability. The old per-param Megatron rules
# (``param_pspec``) are now the engine's ``dp_tp`` rule set.
def batch_sharding(mesh: Mesh):
    """Shard leading (batch) dim over 'data'."""
    from deeplearning4j_tpu.parallel import partition
    return partition.named_sharding(mesh, partition.pspec("data"))


def replicated(mesh: Mesh):
    from deeplearning4j_tpu.parallel import partition
    return partition.named_sharding(mesh)


def shard_params_for_tp(params_tree, conf, mesh: Mesh, model_axis: str = "model"):
    """Apply tensor-parallel shardings to a params pytree (list- or
    dict-style) via the ``dp_tp`` partition rules — Megatron column/row
    splits for dense/attention/MoE weights; indivisible or tiny leaves stay
    replicated. XLA GSPMD inserts the all-gathers/reduce-scatters the
    shardings imply — nothing manual."""
    from deeplearning4j_tpu.parallel import partition
    specs = partition.match_partition_rules(
        partition.dp_tp_rules(model_axis), params_tree, mesh=mesh, conf=conf)
    return partition.device_put(params_tree, mesh, specs)
