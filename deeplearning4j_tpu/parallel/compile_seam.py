"""ONE compile seam for every parallel fit path (SNIPPETS.md [3] pattern).

Every step function that runs on a mesh compiles through
:func:`compile_step`, which takes the step fn + the PartitionSpec trees from
``partition.py`` + the mesh and chooses the strategy:

* ``"jit"`` — GSPMD: ``jax.jit`` with ``in_shardings``/``out_shardings``
  built from the spec trees (``None`` entries inherit the committed
  placement of staged arrays — the batch positions). XLA inserts the
  collectives the layouts imply; this is the sync-DP / TP / ZeRO path.
* ``"shard_map"`` — per-device SPMD bodies (local-SGD, Spark-style
  parameter averaging): ``jax_compat.shard_map`` under an outer jit.
  ``check_vma`` defaults to **False** here: the vma checker rejects
  ``pallas_call``, so a checked body silently downgrades every flash/LSTM
  kernel to XLA math (round-5 advisor finding; ulysses set the precedent).
  Bodies whose outputs are made replicated by their own psum/pmean are safe
  unchecked — pass ``check_vma=True`` only to keep the checker on a body
  that wants the audit and doesn't carry kernels.

The seam preserves what the fit paths already had: buffer donation
(``donate_argnums``), dtype-policy cache keys (the ``cache_key``
pass-through), and CompileTracker registration — with the rule-set name
folded into the cache key so recompiles are attributed per rule set. It
also records the chosen specs (``dl4j_sharding_spec_total``) and, when
given the parameter tree, the per-device sharded-param-bytes gauge.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence, Tuple

import jax

from deeplearning4j_tpu import jax_compat
from deeplearning4j_tpu.observability.compile_tracker import global_tracker
from deeplearning4j_tpu.parallel import partition


@dataclasses.dataclass
class CompiledStep:
    """A compiled, tracker-wrapped step plus the layout that produced it —
    callers read ``in_specs``/``out_specs`` for telemetry and staging."""
    fn: Callable
    name: str
    rule_set: str
    strategy: str
    mesh: Any
    in_specs: Any
    out_specs: Any
    check_vma: bool = True

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


def _sharding_entries(mesh, specs):
    """Per-argument spec entries -> per-argument NamedSharding trees for
    jit; ``None`` entries stay None (inherit the staged placement)."""
    if specs is None:
        return None
    return tuple(partition.tree_shardings(mesh, s) for s in specs)


def compile_step(name: str, step_fn: Callable, *, mesh, rule_set: str,
                 in_specs: Optional[Sequence] = None,
                 out_specs: Any = None,
                 strategy: str = "jit",
                 check_vma: bool = False,
                 donate_argnums: Tuple[int, ...] = (),
                 cache_key: Any = None,
                 params=None, param_specs=None,
                 conf=None, fingerprint: Optional[str] = None) -> CompiledStep:
    """Compile ``step_fn`` for ``mesh`` under the given spec trees.

    ``cache_key`` flows into CompileTracker.wrap with ``rule_set`` prepended,
    so a recompile storm shows which rule set is churning. ``params`` +
    ``param_specs`` (optional) feed the per-device sharded-param-bytes
    gauge for this rule set.

    ``conf`` (the model configuration, when the caller has one) and
    ``fingerprint`` (identity override when ``name`` carries per-instance
    decoration) key the persistent executable cache; sharding strategy,
    spec trees, and donation are folded in so layout changes invalidate.
    """
    if strategy == "shard_map":
        body = jax_compat.shard_map(step_fn, mesh=mesh, in_specs=tuple(in_specs),
                                    out_specs=out_specs, check_vma=check_vma)
        fitted = jax.jit(body, donate_argnums=donate_argnums)
    elif strategy == "jit":
        kw = {}
        in_sh = _sharding_entries(mesh, in_specs)
        if in_sh is not None:
            kw["in_shardings"] = in_sh
        out_sh = partition.tree_shardings(mesh, out_specs) \
            if out_specs is not None else None
        if out_sh is not None:
            kw["out_shardings"] = out_sh
        fitted = jax.jit(step_fn, donate_argnums=donate_argnums, **kw)
    else:
        raise ValueError(f"unknown compile strategy {strategy!r}; "
                         f"expected 'jit' or 'shard_map'")

    partition.record_specs(rule_set, in_specs, out_specs)
    if params is not None and param_specs is not None:
        partition.record_param_bytes(rule_set, params, param_specs, mesh)

    key = cache_key if isinstance(cache_key, tuple) else (cache_key,)
    from deeplearning4j_tpu.nn import compile_cache as _cc

    # fingerprint material: NOT the cache_key (callers fold process-local
    # ids into it for in-memory keying); the global dtype policy stands in
    # for it — conf-pinned dtypes are covered by the conf hash
    try:
        from deeplearning4j_tpu import common
        policy = common.policy_key()
    except Exception:
        policy = None
    tracked = _cc.build_program(
        name, fitted, cache_key=(rule_set,) + key,
        fingerprint=fingerprint or name, conf=conf,
        extra=(rule_set, strategy, repr(in_specs), repr(out_specs),
               tuple(donate_argnums), repr(policy)))
    return CompiledStep(fn=tracked, name=name, rule_set=rule_set,
                        strategy=strategy, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=check_vma)
