"""Expert parallelism: GShard-style all_to_all MoE dispatch over a mesh axis.

Tokens are sharded over the ``expert`` mesh axis (it doubles as a data axis,
the standard EP layout); experts are sharded over the same axis. Each shard
routes its local tokens, packs them into per-expert capacity buffers with a
one-hot dispatch tensor, all_to_alls the buffers so every device receives the
tokens bound for ITS experts from every shard, applies its local experts'
FFNs, all_to_alls back, and combines with the gate weights. Exactly matches
the dense MoELayer math whenever no expert overflows its capacity
(capacity_factor sizes the buffers; overflowing tokens are dropped, as in
GShard/Switch).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

Array = jax.Array


def _moe_local(router_params, expert_params, x, *, layer, axis_name: str,
               capacity: int):
    """Per-shard body. x: [Bl, T, F] local tokens; expert_params hold this
    shard's experts on the leading axis [E_local, ...]."""
    N = lax.psum(1, axis_name)
    E_local = expert_params["W1"].shape[0]
    E = N * E_local
    Bl, T, F = x.shape
    S = Bl * T
    x2d = x.reshape(S, F)

    eidx, gate, _ = layer.route(router_params, x2d)
    # routing/position arithmetic is exact int32/float32 bookkeeping: under
    # the full-bf16 activation policy x2d.dtype can only count to 256 before
    # cumsum slots collide and tokens silently overwrite each other
    sel = jax.nn.one_hot(eidx, E, dtype=jnp.float32)            # [S, E]
    # position of each token within its expert's capacity buffer
    pos = (jnp.cumsum(sel, axis=0) - 1.0) * sel                 # [S, E]
    in_cap = sel * (pos < capacity)
    pos_oh = (jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                             dtype=jnp.float32)
              * in_cap[..., None]).astype(x2d.dtype)            # [S, E, C]
    # pack: [E, C, F] buffers of this shard's tokens per destination expert
    buf = jnp.einsum("sec,sf->ecf", pos_oh, x2d)
    # exchange: every device gets its experts' buffers from every shard.
    # [E, C, F] -> [N, E_local, C, F]; all_to_all over the leading shard axis.
    buf = buf.reshape(N, E_local, capacity, F)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                           # [N, El, C, F]
    # apply local experts to tokens from all shards
    buf = buf.transpose(1, 0, 2, 3).reshape(E_local, N * capacity, F)
    out = layer.expert_ffn(expert_params, buf)                  # [El, N*C, F]
    out = out.reshape(E_local, N, capacity, F).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                           # [N=E grouping back]
    out = out.reshape(E, capacity, F)
    # combine: gather each token's result from its (expert, slot) and gate it
    # (gate cast so the f32 router bookkeeping can't promote the activations)
    y = jnp.einsum("sec,ecf->sf", pos_oh, out) * gate[:, None].astype(out.dtype)
    return y.astype(x2d.dtype).reshape(Bl, T, F)


class ExpertParallelMoE:
    """Run a MoELayer's parameters expert-parallel over ``axis_name``."""

    def __init__(self, layer, mesh: Mesh, axis_name: str = "expert",
                 capacity_factor: float = 2.0):
        self.layer = layer
        self.mesh = mesh
        self.axis_name = axis_name
        self.capacity_factor = capacity_factor
        n = mesh.shape[axis_name]
        if layer.n_experts % n:
            raise ValueError(f"{layer.n_experts} experts not divisible by "
                             f"mesh axis size {n}")

    def __call__(self, params: dict, x: Array) -> Array:
        """x: [B, T, F] with B divisible by the axis size. Returns [B, T, F]."""
        n = self.mesh.shape[self.axis_name]
        B, T, F = x.shape
        if B % n:
            raise ValueError(f"batch {B} not divisible by axis size {n}")
        tokens_per_shard = (B // n) * T
        capacity = max(1, int(self.capacity_factor * tokens_per_shard
                              / self.layer.n_experts))
        router = {"Wg": params["Wg"]}
        experts = {k: params[k] for k in ("W1", "b1", "W2", "b2")}
        fn = shard_map(
            functools.partial(_moe_local, layer=self.layer,
                              axis_name=self.axis_name, capacity=capacity),
            mesh=self.mesh,
            in_specs=({"Wg": P()},
                      {k: P(self.axis_name) for k in experts},
                      P(self.axis_name)),
            out_specs=P(self.axis_name),
        )
        router = jax.device_put(router,
                                {"Wg": NamedSharding(self.mesh, P())})
        experts = jax.device_put(
            experts, {k: NamedSharding(self.mesh, P(self.axis_name))
                      for k in experts})
        x = jax.device_put(x, NamedSharding(self.mesh, P(self.axis_name)))
        # same epilogue as the dense MoELayer.apply (activation after combine)
        return self.layer.act_fn()(fn(router, experts, x))
