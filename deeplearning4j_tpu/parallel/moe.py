"""Expert parallelism: GShard-style all_to_all MoE dispatch over a mesh axis.

Tokens are sharded over the ``expert`` mesh axis (it doubles as a data axis,
the standard EP layout); experts are sharded over the same axis. Each shard
routes its local tokens, packs them into per-expert capacity buffers with a
one-hot dispatch tensor, all_to_alls the buffers so every device receives the
tokens bound for ITS experts from every shard, applies its local experts'
FFNs, all_to_alls back, and combines with the gate weights. Exactly matches
the dense MoELayer math whenever no expert overflows its capacity
(capacity_factor sizes the buffers; overflowing tokens are dropped, as in
GShard/Switch).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh
from deeplearning4j_tpu.parallel.partition import (
    pspec as P, named_sharding as _named_sharding,
)
from deeplearning4j_tpu.jax_compat import shard_map
from deeplearning4j_tpu.observability.names import COLLECTIVE_BYTES_PER_STEP
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)

# trace-time traffic gauge (see parallel/ring_attention.py: the local bodies
# run inside jit traces, so traffic is sized from static shapes per trace)
_collective_per_step = _obs_registry().gauge(
    COLLECTIVE_BYTES_PER_STEP,
    "bytes one executed step moves through a traced collective, from "
    "static shapes at trace time, by op and site")

Array = jax.Array


def _moe_local(router_params, expert_params, x, rng, *, layer,
               axis_name: str, capacity: int, train: bool,
               mean_axes=None):
    """Per-shard body. x: [Bl, T, F] local tokens; expert_params hold this
    shard's experts on the leading axis [E_local, ...]. Returns (y, aux)
    where aux is the GLOBAL Switch load-balance term E * sum_e f_e * P_e
    (token fractions / router probs pmean-ed over the shards — every shard
    holds the same token count, so the pmean of local means is the global
    mean), matching MoELayer._balance_term on the full batch. ``rng``
    (replicated) is folded per shard so router_noise jitters each shard's
    routing independently at train time, like the dense path's jitter —
    same distribution, different draws than single-device."""
    N = lax.psum(1, axis_name)
    E_local = expert_params["W1"].shape[0]
    E = N * E_local
    Bl, T, F = x.shape
    S = Bl * T
    x2d = x.reshape(S, F)

    mean_axes = mean_axes or (axis_name,)
    rng_local = rng
    if rng is not None:
        for ax in mean_axes:
            rng_local = jax.random.fold_in(rng_local, lax.axis_index(ax))
    eidx, gate, probs = layer.route(router_params, x2d, train=train,
                                    rng=rng_local)
    frac = lax.pmean(jnp.mean(jax.nn.one_hot(eidx, E, dtype=jnp.float32),
                              axis=0), mean_axes)
    p_mean = lax.pmean(jnp.mean(probs.astype(jnp.float32), axis=0), mean_axes)
    aux = E * jnp.sum(frac * p_mean)
    # routing/position arithmetic is exact int32/float32 bookkeeping: under
    # the full-bf16 activation policy x2d.dtype can only count to 256 before
    # cumsum slots collide and tokens silently overwrite each other
    sel = jax.nn.one_hot(eidx, E, dtype=jnp.float32)            # [S, E]
    # position of each token within its expert's capacity buffer
    pos = (jnp.cumsum(sel, axis=0) - 1.0) * sel                 # [S, E]
    in_cap = sel * (pos < capacity)
    pos_oh = (jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                             dtype=jnp.float32)
              * in_cap[..., None]).astype(x2d.dtype)            # [S, E, C]
    # pack: [E, C, F] buffers of this shard's tokens per destination expert
    buf = jnp.einsum("sec,sf->ecf", pos_oh, x2d)
    # exchange: every device gets its experts' buffers from every shard.
    # [E, C, F] -> [N, E_local, C, F]; all_to_all over the leading shard axis.
    buf = buf.reshape(N, E_local, capacity, F)
    buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                           # [N, El, C, F]
    # apply local experts to tokens from all shards
    buf = buf.transpose(1, 0, 2, 3).reshape(E_local, N * capacity, F)
    out = layer.expert_ffn(expert_params, buf)                  # [El, N*C, F]
    out = out.reshape(E_local, N, capacity, F).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)                           # [N=E grouping back]
    out = out.reshape(E, capacity, F)
    # combine: gather each token's result from its (expert, slot) and gate it
    # (gate cast so the f32 router bookkeeping can't promote the activations)
    y = jnp.einsum("sec,ecf->sf", pos_oh, out) * gate[:, None].astype(out.dtype)
    return y.astype(x2d.dtype).reshape(Bl, T, F), aux


def expert_parallel_ffn(layer, params: dict, x: Array, mesh: Mesh,
                        axis_name: str, capacity_factor: float = 2.0,
                        train: bool = False, rng=None,
                        seq_axis: str = None):
    """Trace-safe GShard dispatch: the in-jit target MoELayer.apply uses when
    an active ParallelContext declares an expert axis (parallel/context.py).

    x: [B, T, F] (or [S, F], treated as T=1) with B divisible by the axis
    size. Returns (y, aux) — y WITHOUT the layer's output activation (callers
    apply it exactly where their dense path does), aux the global Switch
    load-balance term. Under jit, GSPMD reshards operands to the shard_map
    in_specs, so this composes with the data-parallel wrapper step where the
    data axis doubles as the expert axis (the standard EP layout).
    """
    n = mesh.shape[axis_name]
    squeeze = x.ndim == 2
    if squeeze:
        x = x[:, None, :]
    B, T, F = x.shape
    if B % n:
        raise ValueError(f"batch {B} not divisible by expert axis size {n}")
    # composing with sequence parallelism: shard T over the seq axis too so
    # sp shards route disjoint token slices instead of all-gathering the
    # full sequence and redundantly recomputing the FFN on every sp shard
    if seq_axis is not None and (seq_axis == axis_name
                                 or T % mesh.shape[seq_axis]):
        seq_axis = None
    n_seq = mesh.shape[seq_axis] if seq_axis else 1
    x_spec = P(axis_name, seq_axis) if seq_axis else P(axis_name)
    mean_axes = (axis_name,) + ((seq_axis,) if seq_axis else ())
    capacity = max(1, int(capacity_factor * (B // n) * (T // n_seq)
                          / layer.n_experts))
    # two all-to-alls (dispatch + return) on per-shard [N, E_local, C, F]
    # capacity buffers, across all n shards
    _collective_per_step.labels(op="all_to_all", site="moe_dispatch").set(
        2 * n * n * (layer.n_experts // n) * capacity * F
        * jnp.dtype(x.dtype).itemsize)
    router = {"Wg": params["Wg"]}
    experts = {k: params[k] for k in ("W1", "b1", "W2", "b2")}
    # router noise needs an rng; without one the routing is deterministic,
    # so a placeholder key + train=False keeps the operand list static
    if rng is None:
        rng, train = jax.random.PRNGKey(0), False
    fn = shard_map(
        functools.partial(_moe_local, layer=layer, axis_name=axis_name,
                          capacity=capacity, train=train,
                          mean_axes=mean_axes),
        mesh=mesh,
        in_specs=({"Wg": P()}, {k: P(axis_name) for k in experts},
                  x_spec, P()),
        out_specs=(x_spec, P()),
    )
    y, aux = fn(router, experts, x, rng)
    if squeeze:
        y = y[:, 0, :]
    return y, aux


class ExpertParallelMoE:
    """Run a MoELayer's parameters expert-parallel over ``axis_name``."""

    def __init__(self, layer, mesh: Mesh, axis_name: str = "expert",
                 capacity_factor: float = 2.0):
        self.layer = layer
        self.mesh = mesh
        self.axis_name = axis_name
        self.capacity_factor = capacity_factor
        n = mesh.shape[axis_name]
        if layer.n_experts % n:
            raise ValueError(f"{layer.n_experts} experts not divisible by "
                             f"mesh axis size {n}")

    def __call__(self, params: dict, x: Array) -> Array:
        """x: [B, T, F] with B divisible by the axis size. Returns [B, T, F]."""
        router = jax.device_put({"Wg": params["Wg"]},
                                {"Wg": _named_sharding(self.mesh, P())})
        experts = jax.device_put(
            {k: params[k] for k in ("W1", "b1", "W2", "b2")},
            {k: _named_sharding(self.mesh, P(self.axis_name))
             for k in ("W1", "b1", "W2", "b2")})
        x = jax.device_put(x, _named_sharding(self.mesh, P(self.axis_name)))
        y, _ = expert_parallel_ffn(self.layer, {**router, **experts}, x,
                                   self.mesh, self.axis_name,
                                   self.capacity_factor)
        # same epilogue as the dense MoELayer.apply (activation after combine)
        return self.layer.act_fn()(y)
