"""Elastic preemption-tolerant training over the async parameter server.

Production accelerator pools preempt workers without warning (the reference
shipped deeplearning4j-aws + Spark TrainingMaster for exactly this). The
``ElasticTrainer`` composes three engines that already work alone into a
fleet that survives a worker dying mid-``fit()``:

* **Membership** — ``cloud.MembershipOracle`` (TpuProvisioner grown into
  the membership authority): workers register over the PS transport seam,
  drawing a member id + globally monotonic *fencing epoch* + lease;
  heartbeats renew the lease; a lapsed lease is declared dead server-side.
  The ``ParameterServer`` fences pushes by epoch, so a zombie resumed after
  expiry cannot corrupt the model — its deltas are rejected permanently.

* **Shard handoff** — data assignment flows through the loopback broker's
  committed-offset consumer groups (`streaming/broker.py`): shard *i* is a
  topic consumed under group *i*, workers commit offsets only after a push
  window lands on the PS, and a replacement simply resumes the same group
  at committed+1. At-least-once: a crash redelivers at most one window;
  nothing is ever silently skipped. The coordinator keeps NO assignment
  bookkeeping beyond comparing the group's committed offset to the shard
  topic's ``fin`` marker.

* **Restore-on-join** — a joining worker warm-starts by pulling the current
  ``(version, params)`` from the PS (the normal worker bootstrap). When the
  PS itself restarts, ``fit()`` warm-starts the server from the committed
  async checkpoint sidecar (`utils/sharded_checkpoint.py`) before any
  worker joins — the sidecar-as-commit-marker contract guarantees a torn
  save is never restored.

The coordinator monitors worker processes: a dead process whose shard has
uncommitted samples is replaced (``shard_handoff``); a process whose lease
lapsed while it still runs is a zombie and is SIGKILLed before its
replacement spawns, so one live worker per shard is an invariant.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.cloud import MembershipOracle
from deeplearning4j_tpu.observability.federation import (
    FederatedRegistry, FleetCollector, register_status_provider,
    set_global_federation, set_global_fleet_collector,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry,
)
from deeplearning4j_tpu.observability.names import ELASTIC_HANDOFFS_TOTAL
from deeplearning4j_tpu.observability.tracing import (
    trace_span as _trace_span,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.parallel.param_server import (
    DEFAULT_STALENESS_CAP, ParameterServer, unflatten_tree,
)
from deeplearning4j_tpu.parallel.ps_transport import (
    ParameterServerTcpFrontend,
)
from deeplearning4j_tpu.streaming.broker import BrokerProducer, LoopbackBroker

_handoffs = _obs_registry().counter(
    ELASTIC_HANDOFFS_TOTAL,
    "shard handoffs to a replacement worker after a worker died").labels()


class _Shard:
    """Coordinator-side view of one shard: its topic/group, its fin-marker
    offset, and the worker process generation currently owning it."""

    def __init__(self, shard: int):
        self.shard = shard
        self.topic = f"shard-{shard}"
        self.group = f"shard-{shard}"
        self.fin_offset = -1
        self.committed = -1
        self.gen = 0
        self.name = ""
        self.proc: Optional[subprocess.Popen] = None
        self.done = False
        self.handoffs = 0


class ElasticTrainer:
    """Preemption-tolerant async-PS trainer (the reference's Spark
    TrainingMaster fault-tolerance role, rebuilt on lease epochs + broker
    offsets). Workers are separate OS processes; kill one mid-fit and its
    shard hands off to a freshly registered replacement."""

    def __init__(self, model, workers: int = 2, push_frequency: int = 4,
                 staleness: int = DEFAULT_STALENESS_CAP,
                 compression: str = "none",
                 transport: str = "tcp",
                 server_optimizer: str = "sgd", server_lr: float = 1.0,
                 lease_timeout_s: float = 15.0,
                 respawn: bool = True, max_handoffs_per_shard: int = 4,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_interval_s: float = 30.0,
                 worker_delays: Optional[Sequence[float]] = None,
                 fit_timeout_s: float = 900.0):
        if compression not in ("none", "bf16"):
            raise ValueError(f"unknown compression {compression!r}; "
                             "expected 'none' or 'bf16'")
        if transport not in ("tcp", "shm"):
            raise ValueError(f"unknown transport {transport!r}; "
                             "expected 'tcp' or 'shm'")
        self.model = model
        self.workers = int(workers)
        self.push_frequency = max(1, push_frequency)
        self.staleness = int(staleness)
        self.compression = compression
        self.transport = transport
        self.server_optimizer = server_optimizer
        self.server_lr = server_lr
        self.lease_timeout_s = float(lease_timeout_s)
        self.respawn = bool(respawn)
        self.max_handoffs_per_shard = int(max_handoffs_per_shard)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_interval_s = float(checkpoint_interval_s)
        self.worker_delays = list(worker_delays or [])
        self.fit_timeout_s = float(fit_timeout_s)
        self.server: Optional[ParameterServer] = None
        self.oracle: Optional[MembershipOracle] = None
        self.federation: Optional[FederatedRegistry] = None
        self.collector: Optional[FleetCollector] = None
        self.worker_stats: List[dict] = []
        self.published = 0
        self.restored_from_checkpoint = False
        self._shards: List[_Shard] = []
        self._proc_lock = threading.Lock()
        self._env_conf: Dict[str, object] = {}
        self._ps_port = self._broker_port = 0

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def workers(self, n: int):
            self._kw["workers"] = n
            return self

        def push_frequency(self, n: int):
            self._kw["push_frequency"] = n
            return self

        def staleness(self, cap: int):
            self._kw["staleness"] = cap
            return self

        def compression(self, codec: str):
            self._kw["compression"] = codec
            return self

        def transport(self, kind: str):
            """"tcp" (framed sockets) or "shm" (tensor bytes in per-worker
            shared-memory rings; control verbs stay on the socket;
            auto-falls back to tcp frames when segments can't attach)."""
            self._kw["transport"] = kind
            return self

        def server_optimizer(self, kind: str, lr: float = 1.0):
            self._kw["server_optimizer"] = kind
            self._kw["server_lr"] = lr
            return self

        def lease_timeout(self, seconds: float):
            """Heartbeat lease: a worker silent this long is declared dead,
            its epoch fenced, its shard handed off."""
            self._kw["lease_timeout_s"] = seconds
            return self

        def respawn(self, enabled: bool, max_per_shard: int = 4):
            """Spawn a replacement for a dead worker whose shard still has
            uncommitted samples (the handoff)."""
            self._kw["respawn"] = enabled
            self._kw["max_handoffs_per_shard"] = max_per_shard
            return self

        def checkpoint(self, directory: str, interval_s: float = 30.0):
            """Async sharded checkpoints for PS-restart warm start: fit()
            restores the committed sidecar state before workers join."""
            self._kw["checkpoint_dir"] = directory
            self._kw["checkpoint_interval_s"] = interval_s
            return self

        def worker_delays(self, *delays: float):
            """Fault injection: shard i's worker sleeps delays[i] seconds
            per step (paces chaos tests and the kill benchmark)."""
            self._kw["worker_delays"] = list(delays)
            return self

        def fit_timeout(self, seconds: float):
            self._kw["fit_timeout_s"] = seconds
            return self

        def build(self) -> "ElasticTrainer":
            return ElasticTrainer(self._model, **self._kw)

    @staticmethod
    def builder(model) -> "ElasticTrainer.Builder":
        return ElasticTrainer.Builder(model)

    # ----------------------------------------------------------------- fit
    @_dump_on_unhandled("ElasticTrainer.fit")
    def fit(self, iterator, epochs: int = 1) -> None:
        self._maybe_restore()
        self.oracle = MembershipOracle(
            preemptible=True, lease_timeout_s=self.lease_timeout_s)
        self.server = ParameterServer(
            self.model.params_list, staleness_cap=self.staleness,
            optimizer=self.server_optimizer, server_lr=self.server_lr,
            membership=self.oracle)
        # fleet observability plane: workers push cumulative metric frames
        # over the same PS seam; the oracle's side-effect-free validate
        # fences a zombie's frames exactly like its deltas
        self.federation = FederatedRegistry(validate=self.oracle.validate)
        self.collector = FleetCollector(federation=self.federation)
        set_global_federation(self.federation)
        set_global_fleet_collector(self.collector)
        register_status_provider("elastic", lambda: self.stats)
        frontend = ParameterServerTcpFrontend(
            self.server, federation=self.federation,
            collector=self.collector).start()
        broker = LoopbackBroker().start()
        self._ps_port, self._broker_port = frontend.port, broker.port
        saver = None
        if self.checkpoint_dir is not None:
            from deeplearning4j_tpu.utils.sharded_checkpoint import (
                AsyncShardedSaver)
            saver = AsyncShardedSaver()
        self.worker_stats = []
        self._shards = [_Shard(i) for i in range(self.workers)]
        try:
            with tempfile.TemporaryDirectory(prefix="dl4j_elastic_") as tmp:
                self._publish_shards(broker, iterator, epochs)
                self._write_conf(tmp)
                for shard in self._shards:
                    self._spawn(shard)
                self._monitor(broker, saver)
        finally:
            with self._proc_lock:
                for shard in self._shards:
                    if shard.proc is not None and shard.proc.poll() is None:
                        shard.proc.kill()
            frontend.stop()
            broker.stop()
        self.model.params_list = unflatten_tree(
            self.server.pull_flat()[1], self.server.spec, as_jax=True)
        if saver is not None:
            # final committed state: the next fit()'s PS-restart warm start
            saver.save(self.checkpoint_dir, self.model,
                       step=self.server.version)
            saver.close()

    # ------------------------------------------------------------ restore
    def _maybe_restore(self) -> None:
        """PS-restart warm start: only a COMMITTED checkpoint (sidecar
        present) is restored; a torn async save is ignored by contract."""
        if self.checkpoint_dir is None:
            return
        if not os.path.exists(os.path.join(self.checkpoint_dir,
                                           "meta.json")):
            return
        from deeplearning4j_tpu.utils.sharded_checkpoint import (
            restore_sharded)
        restore_sharded(self.checkpoint_dir, self.model)
        self.restored_from_checkpoint = True
        _flight_recorder().record(
            "elastic_restore", directory=self.checkpoint_dir,
            iteration=self.model.iteration)

    # ------------------------------------------------------------- publish
    def _publish_shards(self, broker: LoopbackBroker, iterator,
                        epochs: int) -> None:
        batches = []
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            batches.extend(iterator)
        producer = BrokerProducer(broker.address)
        try:
            for shard in self._shards:
                # one trace root per shard: every message carries this
                # span's traceparent, so consume + push stitch under it
                with _trace_span("shard.publish", topic=shard.topic,
                                 shard=shard.shard):
                    for ds in batches[shard.shard::self.workers]:
                        producer.publish(
                            shard.topic,
                            {"x": np.asarray(ds.features),  # lint: host-sync-in-hot-loop-ok (one-time shard publication before workers spawn, not a train loop)
                             "y": np.asarray(ds.labels)})  # lint: host-sync-in-hot-loop-ok (one-time shard publication before workers spawn, not a train loop)
                        self.published += 1
                    # the fin marker closes the shard: a group whose
                    # committed offset reaches it has consumed every sample
                    # at least once
                    shard.fin_offset = producer.publish(
                        shard.topic, {}, meta={"fin": True})
        finally:
            producer.close()

    def _write_conf(self, tmp: str) -> None:
        from deeplearning4j_tpu.nn.conf.serde import to_json
        conf_path = os.path.join(tmp, "conf.json")
        with open(conf_path, "w") as f:
            f.write(to_json(self.model.conf))
        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU relay in workers
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep
                             + env.get("PYTHONPATH", ""))
        from deeplearning4j_tpu.nn import compile_cache
        if compile_cache.enabled():
            # pin the resolved executable-cache dir so every worker
            # generation shares it: gen-0 writes the step executable,
            # a respawned replacement warm-loads it and skips XLA
            env["DL4J_COMPILE_CACHE_DIR"] = compile_cache.cache_dir()
        rec = _flight_recorder()
        if rec.dump_dir:
            # same pinning for the flight-recorder dir: a set_dump_dir()
            # call on the coordinator never reaches os.environ, so without
            # this a dead worker's last bundle lands nowhere the fleet
            # collector can find it
            env["DL4J_FLIGHT_RECORDER_DIR"] = rec.dump_dir
        self._env_conf = {"env": env, "conf": conf_path}

    def _delay(self, shard: int) -> float:
        if shard < len(self.worker_delays):
            return float(self.worker_delays[shard])
        return 0.0

    # --------------------------------------------------------------- spawn
    def _spawn(self, shard: _Shard) -> None:
        shard.name = f"shard{shard.shard}-gen{shard.gen}"
        cmd = [sys.executable, "-m",
               "deeplearning4j_tpu.parallel.ps_worker",
               "--addr", f"127.0.0.1:{self._ps_port}",
               "--conf", self._env_conf["conf"],
               "--broker", f"127.0.0.1:{self._broker_port}",
               "--topic", shard.topic, "--group", shard.group,
               "--shard", str(shard.shard),
               "--worker-name", shard.name,
               "--push-frequency", str(self.push_frequency),
               "--codec", self.compression,
               "--ps-transport", self.transport,
               "--delay", str(self._delay(shard.shard))]
        with self._proc_lock:
            shard.proc = subprocess.Popen(
                cmd, env=self._env_conf["env"], stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True)
        shard.gen += 1

    def chaos_kill(self, shard: int) -> bool:
        """Fault injection for tests/benchmarks: SIGKILL (never graceful)
        the process currently owning ``shard``. Returns True if a live
        process was killed."""
        with self._proc_lock:
            s = self._shards[shard]
            if s.proc is None or s.proc.poll() is not None:
                return False
            s.proc.kill()
        _flight_recorder().record("elastic_chaos_kill", shard=shard,
                                  worker=s.name)
        return True

    # -------------------------------------------------------------- monitor
    def _monitor(self, broker: LoopbackBroker, saver) -> None:
        deadline = time.time() + self.fit_timeout_s
        last_ckpt = time.time()
        while not all(s.done for s in self._shards):
            if time.time() > deadline:
                raise RuntimeError(
                    f"elastic fit exceeded {self.fit_timeout_s:.0f}s; "
                    f"shards done: {[s.done for s in self._shards]}")
            self.oracle.expire()
            for shard in self._shards:
                if shard.done:
                    continue
                self._tend(shard, broker)
            _wd_beat(self.server.version)
            if (saver is not None
                    and time.time() - last_ckpt
                    > self.checkpoint_interval_s):
                self._snapshot(saver)
                last_ckpt = time.time()
            time.sleep(0.05)

    def _tend(self, shard: _Shard, broker: LoopbackBroker) -> None:
        lease = self.oracle.member_by_name(shard.name)
        rc = shard.proc.poll()
        if rc is None:
            if (lease is not None and not lease.alive
                    and lease.reason == "lease-lapsed"):
                # zombie: the oracle declared it dead but the process still
                # runs (wedged/paused). Its pushes are already fenced; kill
                # the body so exactly one worker owns the shard before the
                # replacement spawns.
                shard.proc.kill()
            return
        stdout, stderr = shard.proc.communicate()
        committed = shard.committed = broker.committed(shard.topic,
                                                       shard.group)
        if rc == 0:
            shard.done = True
            try:
                self.worker_stats.append(
                    json.loads(stdout.strip().splitlines()[-1]))
            except (ValueError, IndexError):
                _flight_recorder().record(
                    "elastic_stats_unparsed", worker=shard.name)
            return
        if lease is not None and lease.alive:
            self.oracle.evict(lease.member, reason=f"exit-rc{rc}")
        if committed >= shard.fin_offset:
            # died after committing its fin marker: every sample in the
            # shard is consumed; no replacement needed
            shard.done = True
            return
        if self.respawn and shard.handoffs < self.max_handoffs_per_shard:
            shard.handoffs += 1
            _handoffs.inc()
            _flight_recorder().record(
                "shard_handoff", shard=shard.shard, gen=shard.gen,
                committed=committed, fin=shard.fin_offset, rc=rc)
            if self.collector is not None:
                # a handoff is exactly the moment one process's ring is not
                # enough: capture the whole fleet's view of the death
                self.collector.dump(reason="shard-handoff")
            self._spawn(shard)
            return
        raise RuntimeError(
            f"elastic worker for shard {shard.shard} died (rc={rc}) with "
            f"uncommitted samples and no respawn budget:\n" + stderr[-2000:])

    def _snapshot(self, saver) -> None:
        # the coordinator's model object is a snapshot vehicle: restore the
        # server's current vector into it, then async-save (the sidecar
        # commits only after the background write lands)
        self.model.params_list = unflatten_tree(
            self.server.pull_flat()[1], self.server.spec, as_jax=True)
        saver.save(self.checkpoint_dir, self.model,
                   step=self.server.version)

    # ----------------------------------------------------------- accessors
    @property
    def handoffs(self) -> int:
        return sum(s.handoffs for s in self._shards)

    @property
    def shard_commits(self) -> List[dict]:
        """Per-shard accounting after fit(): the group's final committed
        offset vs the topic's fin marker. ``committed >= fin`` is the
        no-window-silently-dropped proof (the chaos test's acceptance)."""
        return [{"shard": s.shard, "committed": s.committed,
                 "fin": s.fin_offset, "handoffs": s.handoffs}
                for s in self._shards]

    @property
    def stats(self) -> dict:
        return {
            "published": self.published,
            "steps": sum(int(s.get("steps", 0)) for s in self.worker_stats),
            "handoffs": self.handoffs,
            "fenced": self.server.fenced if self.server else 0,
            "lease_expiries": (self.oracle.lease_expiries
                               if self.oracle else 0),
            "joins": self.oracle.joins if self.oracle else 0,
            "restored": self.restored_from_checkpoint,
        }
