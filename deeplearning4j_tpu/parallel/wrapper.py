"""ParallelWrapper: data-parallel training over a device mesh.

Reference: deeplearning4j-scaleout ParallelWrapper.java:44 — clones the model into one
trainer thread per device, round-robin feeds minibatches, averages params every
``averaging_frequency`` iterations via Nd4j.averageAndPropagate (:179) and optionally
averages updater state (:198-212).

TPU-native redesign — no threads, no clones, no explicit averaging transport:

* averaging_frequency == 1 (synchronous DP): ONE jit-compiled train step whose batch
  input is sharded over the 'data' mesh axis and whose params are replicated. The loss
  is the global-batch mean, so autodiff's gradients are automatically all-reduced by
  XLA (psum over ICI) — bitwise the same math as lockstep parameter averaging every
  iteration, with the collective fused into the step.

* averaging_frequency == N > 1 (local SGD, the reference's actual semantics): params
  carry a leading per-replica axis sharded over 'data'; a shard_map train step updates
  each replica locally from its shard of the batch, and every N iterations a psum-mean
  resynchronizes params (and optionally updater state) across replicas.

The same wrapper covers the Spark ParameterAveragingTrainingMaster use-case
(SURVEY.md §2.4): multi-host, the mesh just spans hosts via jax.distributed and the
collectives ride DCN.
"""
from __future__ import annotations

import functools
import time as _time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.observability.compile_tracker import (
    global_tracker as _compile_tracker,
)
from deeplearning4j_tpu.observability.flight_recorder import (
    dump_on_unhandled as _dump_on_unhandled,
    global_recorder as _flight_recorder,
)
from deeplearning4j_tpu.observability.watchdog import beat as _wd_beat
from deeplearning4j_tpu.observability.names import (
    COLLECTIVE_BYTES_TOTAL, FIT_PHASE_SECONDS,
)
from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry, tree_nbytes as _tree_nbytes,
)
from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
from deeplearning4j_tpu.parallel.compile_seam import compile_step
from deeplearning4j_tpu.parallel.partition import (
    pspec as P, named_sharding as _named_sharding, match_partition_rules,
    rules_for,
)

# step-time attribution shares the fit-phase histogram with the single-chip
# loops; the collective counter sizes DP traffic host-side per dispatch (the
# gradient psum moves ~param bytes per step; traced collectives inside
# ring/ulysses/moe report trace-time per-step gauges instead)
_phase_hist = _obs_registry().histogram(
    FIT_PHASE_SECONDS,
    "host wall seconds per fit-loop phase (staging: host cast+transfer "
    "submit, or with device prefetch the visible wait for the staged batch; "
    "dispatch: jitted-call submit; listeners: callback overhead)")
_t_staging = _phase_hist.labels(phase="staging")
_t_dispatch = _phase_hist.labels(phase="dispatch")
_t_listeners = _phase_hist.labels(phase="listeners")
_collective_bytes = _obs_registry().counter(
    COLLECTIVE_BYTES_TOTAL,
    "bytes moved by host-dispatched collectives, by op and site")


class ParallelWrapperBuilder:
    """Mirrors reference ParallelWrapper.Builder (:483+)."""

    def __init__(self, model):
        self._model = model
        self._workers: Optional[int] = None
        self._prefetch = 2
        self._avg_freq = 1
        self._average_updaters = True
        self._report_score = False
        self._mesh: Optional[Mesh] = None
        self._seq_axis: Optional[str] = None
        self._seq_mode = "ulysses"
        self._expert_axis: Optional[str] = None
        self._capacity_factor = 2.0
        self._zero1 = False
        self._fsdp = False
        self._sharding: Optional[str] = None

    def workers(self, n: int) -> "ParallelWrapperBuilder":
        self._workers = n
        return self

    def prefetch_buffer(self, n: int) -> "ParallelWrapperBuilder":
        self._prefetch = n
        return self

    def averaging_frequency(self, n: int) -> "ParallelWrapperBuilder":
        self._avg_freq = max(1, n)
        return self

    def average_updaters(self, flag: bool) -> "ParallelWrapperBuilder":
        self._average_updaters = flag
        return self

    def report_score_after_averaging(self, flag: bool) -> "ParallelWrapperBuilder":
        self._report_score = flag
        return self

    def mesh(self, mesh: Mesh) -> "ParallelWrapperBuilder":
        self._mesh = mesh
        return self

    def sequence_parallel(self, axis: str = "sp",
                          mode: str = "ulysses") -> "ParallelWrapperBuilder":
        """Run the net's attention layers sequence-parallel over the mesh
        axis ``axis`` (Ulysses all_to_all or "ring" ppermute) — long-context
        training from a plain transformer config, no model changes."""
        self._seq_axis = axis
        self._seq_mode = mode
        return self

    def expert_parallel(self, axis: str = "data",
                        capacity_factor: float = 2.0) -> "ParallelWrapperBuilder":
        """Route the net's MoE layers through GShard all_to_all dispatch over
        ``axis`` (default: the data axis doubles as the expert axis — the
        standard EP layout)."""
        self._expert_axis = axis
        self._capacity_factor = capacity_factor
        return self

    def shard_parameters(self, flag: bool = True) -> "ParallelWrapperBuilder":
        """FSDP / ZeRO-3: shard the parameters themselves over the data
        axis — per-device parameter memory drops by the axis size; XLA
        all-gathers each weight just-in-time and reduce-scatters its
        gradient. Usually combined with .shard_optimizer_state(). Same
        memory-feature caveats as ZeRO-1 apply."""
        self._fsdp = flag
        return self

    def shard_optimizer_state(self, flag: bool = True) -> "ParallelWrapperBuilder":
        """ZeRO-1: shard updater state (Adam moments etc.) over the data
        axis — per-device optimizer memory drops by the axis size; XLA
        inserts the gather around the parameter update. This is a MEMORY
        feature: training math is exactly unchanged (tested), and GSPMD may
        log involuntary-remat warnings where the sharding propagates through
        reshapes in the backward pass — a compile-time layout fallback on
        small tensors, not a correctness issue. Profile before assuming a
        throughput effect either way."""
        self._zero1 = flag
        return self

    def sharding(self, rule_set: str) -> "ParallelWrapperBuilder":
        """Pick a partition-rule set by name (parallel/partition.py):

        * ``"dp"`` — replicate params, shard the batch (the default).
        * ``"dp_tp"`` — Megatron tensor parallelism over the 'model' mesh
          axis on top of data parallelism (mesh must carry both axes).
        * ``"zero3"`` — params AND optimizer state sharded over 'data'
          (equivalent to .shard_parameters() + .shard_optimizer_state()).

        This is the config-choice face of the engine: the mesh shape plus a
        rule-set name replaces hand-wired sharding code paths."""
        self._sharding = rule_set
        return self

    def build(self) -> "ParallelWrapper":
        return ParallelWrapper(self._model, workers=self._workers,
                               prefetch=self._prefetch,
                               averaging_frequency=self._avg_freq,
                               average_updaters=self._average_updaters,
                               report_score=self._report_score, mesh=self._mesh,
                               sequence_parallel_axis=self._seq_axis,
                               sequence_parallel_mode=self._seq_mode,
                               expert_parallel_axis=self._expert_axis,
                               capacity_factor=self._capacity_factor,
                               shard_optimizer_state=self._zero1,
                               shard_parameters=self._fsdp,
                               sharding=self._sharding)


class ParallelWrapper:
    def __init__(self, model, workers: Optional[int] = None, prefetch: int = 2,
                 averaging_frequency: int = 1, average_updaters: bool = True,
                 report_score: bool = False, mesh: Optional[Mesh] = None,
                 sequence_parallel_axis: Optional[str] = None,
                 sequence_parallel_mode: str = "ulysses",
                 expert_parallel_axis: Optional[str] = None,
                 capacity_factor: float = 2.0,
                 shard_optimizer_state: bool = False,
                 shard_parameters: bool = False,
                 sharding: Optional[str] = None):
        self.model = model
        self.mesh = mesh or data_parallel_mesh(workers)
        self.n_workers = self.mesh.shape["data"]
        self.seq_axis = sequence_parallel_axis
        self.seq_mode = sequence_parallel_mode
        self.expert_axis = expert_parallel_axis
        self.capacity_factor = capacity_factor
        self.zero1 = shard_optimizer_state
        self.fsdp = shard_parameters
        if sharding not in (None, "dp", "dp_tp", "zero3"):
            raise ValueError(f"unknown sharding rule set {sharding!r}; "
                             "expected 'dp', 'dp_tp', or 'zero3'")
        self.rule_set = sharding
        if sharding == "zero3":
            # zero3 = the full decomposition: params AND optimizer state
            # sharded over 'data'; the flags below drive the spec trees
            self.zero1 = self.fsdp = True
        if sharding == "dp_tp":
            if "model" not in self.mesh.shape:
                raise ValueError("sharding('dp_tp') needs a mesh with a "
                                 "'model' axis, e.g. build_mesh({'data': 4, "
                                 "'model': 2})")
            if averaging_frequency != 1:
                raise ValueError("sharding('dp_tp') requires "
                                 "averaging_frequency == 1 (synchronous DP)")
        if (self.zero1 or self.fsdp) and averaging_frequency != 1:
            raise ValueError("shard_optimizer_state/shard_parameters "
                             "(ZeRO/FSDP) require averaging_frequency == 1 "
                             "(synchronous DP)")
        if (self.seq_axis or self.expert_axis) and averaging_frequency != 1:
            # the local-SGD step is itself a shard_map over 'data'; nesting
            # the SP/EP shard_maps inside it is not supported
            raise ValueError("sequence/expert parallelism requires "
                             "averaging_frequency == 1 (synchronous DP)")
        if self.seq_axis:
            # requested SP must engage or fail loudly (same principle as EP
            # below): without attention layers the context changes nothing
            layers = list(getattr(model.conf, "layers", []) or [])
            for v in getattr(model.conf, "vertices", {}).values():
                if getattr(v, "layer", None) is not None:
                    layers.append(v.layer)
            attn = [l for l in layers
                    if hasattr(l, "n_heads") and hasattr(l, "causal")]
            if not attn:
                raise ValueError("sequence_parallel() requested but the "
                                 "model has no attention layers")
            n = self.mesh.shape[self.seq_axis]
            if self.seq_mode == "ulysses":
                bad = [l.n_heads for l in attn if l.n_heads % n]
                if bad:
                    raise ValueError(
                        f"sequence_parallel('{self.seq_axis}', ulysses) with "
                        f"axis size {n}: head counts {bad} are not divisible "
                        "by it (use mode='ring' or adjust heads)")
        if self.expert_axis:
            # requested EP must engage or fail loudly — the layer-side
            # dispatch falls back to dense when expert counts don't divide
            # the axis, which must never happen silently for an explicit
            # .expert_parallel() request (ulysses raises on the analogous
            # heads-divisibility violation)
            n = self.mesh.shape[self.expert_axis]
            layers = list(getattr(model.conf, "layers", []) or [])
            for v in getattr(model.conf, "vertices", {}).values():
                if getattr(v, "layer", None) is not None:
                    layers.append(v.layer)
            moe_layers = [l for l in layers if hasattr(l, "n_experts")]
            bad = [l.n_experts for l in moe_layers if l.n_experts % n]
            if bad:
                raise ValueError(
                    f"expert_parallel('{self.expert_axis}') with axis size "
                    f"{n}: expert counts {bad} are not divisible by it")
            if not moe_layers:
                raise ValueError("expert_parallel() requested but the model "
                                 "has no MoE layers")
        self.prefetch = prefetch
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.report_score = report_score
        self._sync_step = None
        self._sync_multi = None
        self._local_step = None
        self._avg_fn = None
        self._local = None  # stacked per-replica (params, states, upd) for local-SGD
        # dtype policy the cached jitted programs were traced under; they are
        # rebuilt when it changes (the policy is read at trace time)
        self._traced_policy = None

    def _drop_stale_programs(self) -> None:
        from deeplearning4j_tpu import common
        eff = common.effective_policy_key(
            getattr(self.model.conf.global_conf, "dtype", None))
        if self._traced_policy != eff:
            self._traced_policy = eff
            self._sync_step = self._sync_multi = None
            self._local_step = self._avg_fn = None

    @staticmethod
    def builder(model) -> ParallelWrapperBuilder:
        return ParallelWrapperBuilder(model)

    def _trace_ctx(self):
        """Context the jitted step's Python body is traced under: publishes
        the mesh + axis roles so attention/MoE layers dispatch their
        sequence-/expert-parallel paths (parallel/context.py)."""
        if self.seq_axis or self.expert_axis:
            from deeplearning4j_tpu.parallel import context as pctx
            return pctx.parallel_context(
                self.mesh, seq_axis=self.seq_axis, seq_mode=self.seq_mode,
                expert_axis=self.expert_axis,
                capacity_factor=self.capacity_factor, data_axis="data")
        import contextlib
        return contextlib.nullcontext()

    def _batch_spec(self, arr) -> P:
        """Leading dim over 'data'; with sequence parallelism active, the
        time axis of [B, T, ...] batches is additionally sharded over the
        sequence axis so long sequences never materialize unsharded.

        Divisibility is validated HERE, at staging, so a bad sequence length
        raises with the axis and length named instead of surfacing as an
        opaque device_put/sharding failure deep inside jit dispatch."""
        if self.seq_axis and getattr(arr, "ndim", 0) == 3:
            n = self.mesh.shape[self.seq_axis]
            t = arr.shape[1]
            if t % n:
                raise ValueError(
                    f"sequence_parallel('{self.seq_axis}'): sequence length "
                    f"{t} (axis 1 of a batch shaped {tuple(arr.shape)}) is "
                    f"not divisible by the '{self.seq_axis}' mesh axis size "
                    f"{n}; pad or re-bucket the batch")
            return P("data", self.seq_axis)
        return P("data")

    def _rule_label(self) -> str:
        """Rule-set name for telemetry + CompileTracker attribution."""
        if self.rule_set:
            return self.rule_set
        if self.fsdp or self.zero1:
            return "zero3"
        return "dp"

    def _matched_specs(self, rules, tree, what: str):
        """Run the partition-rule engine over a param-shaped pytree; an
        explicit sharding request that would shard NOTHING raises (same
        engage-or-fail principle as the expert_parallel validation —
        indivisible leaves demote to replicated per-leaf, but a fully
        replicated result means the request silently did nothing)."""
        specs = match_partition_rules(rules, tree, mesh=self.mesh,
                                      conf=self.model.conf)
        leaves = jax.tree_util.tree_leaves(tree)
        if leaves and all(s == P() for s in jax.tree_util.tree_leaves(specs)):
            raise ValueError(
                f"{what}: no dimension is divisible by the mesh axis; "
                f"nothing would shard")
        return specs

    def _spec_trees(self):
        """(param_specs, upd_specs) from the rule engine — either a P()
        prefix (replicated) or full spec pytrees. dp_tp applies the Megatron
        column/row rules to params AND their optimizer moments; fsdp/zero1/
        zero3 apply the first-divisible-dim ZeRO scan over 'data' (GSPMD
        all-gathers each weight just-in-time and reduce-scatters its
        gradient — per-device memory drops n_workers-fold)."""
        net = self.model
        if self.rule_set == "dp_tp":
            rules = rules_for("dp_tp")
            par = self._matched_specs(rules, net.params_list,
                                      "sharding('dp_tp')")
            upd = match_partition_rules(rules, net.updater_state,
                                        mesh=self.mesh, conf=net.conf)
            return par, upd
        par, upd = P(), P()
        if self.fsdp:
            par = self._matched_specs(rules_for("zero3"), net.params_list,
                                      "shard_parameters()")
        if self.zero1:
            upd = self._matched_specs(rules_for("zero3"), net.updater_state,
                                      "shard_optimizer_state()")
        return par, upd

    # ------------------------------------------------------------------ public API
    @_dump_on_unhandled("ParallelWrapper.fit")
    def fit(self, iterator, epochs: int = 1) -> None:
        """Reference fit(DataSetIterator):322. Batches are sharded over the mesh;
        each global batch must be divisible by the number of workers."""
        if self.prefetch:
            iterator = AsyncDataSetIterator(iterator, queue_size=self.prefetch)
        if self.averaging_frequency == 1:
            self._fit_sync(iterator, epochs)
        else:
            self._fit_local_sgd(iterator, epochs)

    # ------------------------------------------------------- synchronous DP (freq=1)
    def _make_sync_step(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

        net = self.model
        mesh = self.mesh
        if isinstance(net, MultiLayerNetwork):
            base = make_train_step(net.conf)
        else:
            from deeplearning4j_tpu.nn.graph_network import make_graph_train_step
            base = make_graph_train_step(net.conf)

        def step(params, states, upd, x, y, rng, it):
            with self._trace_ctx():
                return base(params, states, upd, x, y, rng, it)

        # batch in_shardings are left to the staged arrays' committed
        # shardings (_stage picks P('data') or P('data', seq_axis) per rank).
        # The cross-replica gradient psum GSPMD inserts for the sharded-batch
        # mean loss inherits the cotangent dtype: under a grad_accum_dtype
        # policy the weight-grad contractions emit wide (f32) cotangents
        # (preferred_element_type routing in the layers), so the DP reduce
        # itself accumulates wide — no extra plumbing needed here.
        par_sp, upd_sp = self._spec_trees()
        return compile_step(
            "ParallelWrapper.sync_step", step, mesh=mesh,
            rule_set=self._rule_label(),
            in_specs=(par_sp, P(), upd_sp, None, None, P(), P()),
            out_specs=(par_sp, P(), upd_sp, P()),
            strategy="jit", cache_key=self._traced_policy,
            params=net.params_list, param_specs=par_sp,
            conf=net.conf)

    def _make_sync_multistep(self):
        """K-step scanned train step with the stacked batch axis sharded over
        'data' (stack axis unsharded): one host dispatch drives K synchronous
        DP steps, so dispatch latency amortizes exactly as in the single-chip
        fast path (MultiLayerNetwork.fit_iterator)."""
        from deeplearning4j_tpu.nn.multilayer import (
            MultiLayerNetwork, make_multistep_train_step)

        net = self.model
        mesh = self.mesh
        if isinstance(net, MultiLayerNetwork):
            base = make_multistep_train_step(net.conf)
        else:
            from deeplearning4j_tpu.nn.graph_network import (
                make_graph_multistep_train_step)
            base = make_graph_multistep_train_step(net.conf)

        def multi(params, states, upd, xs, ys, rng, it0):
            with self._trace_ctx():
                return base(params, states, upd, xs, ys, rng, it0)

        par_sp, upd_sp = self._spec_trees()
        return compile_step(
            "ParallelWrapper.sync_multistep", multi, mesh=mesh,
            rule_set=self._rule_label(),
            in_specs=(par_sp, P(), upd_sp, None, None, P(), P()),
            out_specs=(par_sp, P(), upd_sp, P()),
            strategy="jit", cache_key=self._traced_policy, conf=net.conf)

    def _stage(self, arr, spec: P):
        """Host batch -> device array laid out for the jit's in_shardings.

        Single-process: a plain transfer (the jit places it). Multi-process
        (jax.distributed cluster): every process holds the same global batch
        from its iterator, and each contributes only its addressable shards
        via make_array_from_callback — the cross-host equivalent of the
        reference's Spark executors each taking their partition of the RDD
        (ParameterAveragingTrainingMaster.executeTraining:344)."""
        arr = np.asarray(arr)
        sharding = _named_sharding(self.mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(jnp.asarray(arr), sharding)
        return jax.make_array_from_callback(arr.shape, sharding,
                                            lambda idx: arr[idx])

    def _fit_sync(self, iterator, epochs: int) -> None:
        net = self.model
        self._drop_stale_programs()
        if self._sync_step is None:
            self._sync_step = self._make_sync_step()
            self._sync_multi = self._make_sync_multistep()
        from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM
        from deeplearning4j_tpu.nn.graph_network import (
            ComputationGraph, _coerce_graph_batch)
        from deeplearning4j_tpu.utils.batching import k_step_groups

        is_graph = isinstance(net, ComputationGraph)
        iters_cfg = max(1, net.conf.global_conf.iterations)
        tbptt_lstm = (not is_graph
                      and net.conf.backprop_type == "TruncatedBPTT"
                      and any(isinstance(l, LSTM) for l in net.conf.layers))
        k = max(1, getattr(net, "dispatch_ksteps", 8))

        def to_batch(ds):
            # Fall back to the model's own per-batch path for semantics the
            # sharded standard step doesn't implement: masks, iterations>1,
            # TBPTT state threading. Fallback runs unsharded — correctness
            # over parallelism for these batches.
            if tbptt_lstm or iters_cfg > 1:
                return None
            if is_graph:
                xs, ys, fm, lm = _coerce_graph_batch(ds)
                if fm is not None or lm is not None:
                    return None
                return ([np.asarray(a) for a in xs],  # lint: host-sync-in-hot-loop-ok (host staging in to_batch)
                        [np.asarray(a) for a in ys])  # lint: host-sync-in-hot-loop-ok (host staging in to_batch)
            if ds.features_mask is not None or ds.labels_mask is not None:
                return None
            # lint: host-sync-in-hot-loop-ok (host staging of iterator output, not a device sync)
            return np.asarray(ds.features), np.asarray(ds.labels)

        def fallback(ds):
            if is_graph:
                net._fit_batch(*_coerce_graph_batch(ds))
            else:
                net._fit_batch(ds.features, ds.labels, ds.features_mask,
                               ds.labels_mask)

        # DP gradient psum moves ~param bytes per executed train step; sized
        # host-side here because the collective itself is inside the jit
        param_bytes = _tree_nbytes(net.params_list)
        psum_bytes = _collective_bytes.labels(op="psum_grad",
                                              site="wrapper_sync")

        def dispatch_one(x, y, batch_size):
            if not is_graph:
                net.last_batch_size = batch_size
            t0 = _time.perf_counter()
            (net.params_list, net.state_list, net.updater_state, loss) = \
                self._sync_step(net.params_list, net.state_list,
                                net.updater_state, x, y, net._next_rng(),
                                jnp.int32(net.iteration))
            dt = _time.perf_counter() - t0
            _t_dispatch.observe(dt)
            _compile_tracker().note_step(fn="ParallelWrapper.sync_step")
            psum_bytes.inc(param_bytes)
            _flight_recorder().record(
                "step", path="ParallelWrapper.sync_step", it=net.iteration,
                batch=batch_size, dispatch_s=dt,
                collective_bytes=param_bytes)
            net.score_value = loss  # synced lazily (LazyScore)
            net.iteration += 1
            with _t_listeners.time():
                for listener in net.listeners:
                    listener.iteration_done(net, net.iteration)
            _wd_beat(net.iteration)

        def stack_spec(arr):
            # stacked (K, B, ...) batches: batch spec shifted one axis right
            return P(None, *self._batch_spec(arr[0]))

        def dispatch(xs, ys, n):
            if not is_graph:
                net.last_batch_size = int(xs.shape[1])
            t0 = _time.perf_counter()
            (net.params_list, net.state_list, net.updater_state,
             losses) = \
                self._sync_multi(net.params_list, net.state_list,
                                 net.updater_state, xs, ys,
                                 net._next_rng(),
                                 jnp.int32(net.iteration))
            dt = _time.perf_counter() - t0
            _t_dispatch.observe(dt)
            _compile_tracker().note_step(n, fn="ParallelWrapper.sync_multistep")
            psum_bytes.inc(param_bytes * n)
            _flight_recorder().record(
                "step", path="ParallelWrapper.sync_multistep",
                it=net.iteration, k=n, batch=net.last_batch_size,
                dispatch_s=dt, collective_bytes=param_bytes * n)
            with _t_listeners.time():
                for i in range(n):
                    net.iteration += 1
                    net.score_value = (lambda ls=losses, j=i: ls[j])
                    for listener in net.listeners:
                        listener.iteration_done(net, net.iteration)
            _wd_beat(net.iteration)

        def stage(kind_item):
            # producer thread: the sharded version of the single-chip stage —
            # stack + non-blocking device_put laid out per _batch_spec (or
            # per-process shards via make_array_from_callback), so the
            # sharded (K, B, ...) group is in flight while the previous
            # dispatch executes. Singles fall through to the host fallback.
            kind, item = kind_item
            if kind != "group":
                return kind_item
            if len(item) == 1:
                x, y = item[0]
                if is_graph:
                    bs = int(np.shape(x[0])[0]) if x else 0
                    x = [self._stage(a, self._batch_spec(a)) for a in x]
                    y = [self._stage(a, self._batch_spec(a)) for a in y]
                else:
                    bs = int(np.shape(x)[0])
                    x = self._stage(x, self._batch_spec(x))
                    y = self._stage(y, self._batch_spec(y))
                return "staged1", (x, y, bs)
            if is_graph:
                xs = [self._stage(a, stack_spec(a))
                      for a in (np.stack([b[0][i] for b in item])
                                for i in range(len(item[0][0])))]
                ys = [self._stage(a, stack_spec(a))
                      for a in (np.stack([b[1][i] for b in item])
                                for i in range(len(item[0][1])))]
            else:
                xs = np.stack([b[0] for b in item])
                xs = self._stage(xs, stack_spec(xs))
                ys = np.stack([b[1] for b in item])
                ys = self._stage(ys, stack_spec(ys))
            return "stagedK", (xs, ys, len(item))

        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher

        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            pf = DevicePrefetcher(k_step_groups(iterator, k, to_batch), stage,
                                  depth=self.prefetch, path="wrapper_sync",
                                  wait_series=_t_staging)
            for kind, item in pf:
                if kind == "single":
                    fallback(item)
                elif kind == "staged1":
                    dispatch_one(*item)
                else:
                    dispatch(*item)

    # --------------------------------------------------- local SGD (freq=N>1)
    def _make_local_sgd_fns(self):
        """shard_map local step over stacked per-replica params + psum-mean averager
        (reference averaging loop ParallelWrapper.java:179-212)."""
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, make_graph_train_step
        from deeplearning4j_tpu.nn.multilayer import make_train_step

        net = self.model
        mesh = self.mesh
        if isinstance(net, ComputationGraph):
            # multi-IO supported: xs/ys arrive as lists of arrays; the
            # shard_map in_specs below are pytree prefixes so P("data")
            # applies to every input/label leaf (reference ParallelWrapper
            # handles MultiDataSet fit the same way, ParallelWrapper.java:117)
            base = make_graph_train_step(net.conf)
        else:
            base = make_train_step(net.conf)
        stacked = P("data")
        repl = P()

        def local_step(params, states, upd, x, y, rng, it):
            # inside shard_map: leading axis is this replica's slice (size 1); drop it
            sq = functools.partial(jax.tree_util.tree_map, lambda a: a[0])
            ex = functools.partial(jax.tree_util.tree_map, lambda a: a[None])
            p, s, u = sq(params), sq(states), sq(upd)
            rng_local = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            p2, s2, u2, loss = base(p, s, u, x, y, rng_local, it)
            return ex(p2), ex(s2), ex(u2), jax.lax.pmean(loss, "data")

        # check_vma=False through the seam: the vma checker rejects
        # pallas_call, so a checked body would silently downgrade flash/LSTM
        # kernels to XLA math inside every local step — the outputs are made
        # replicated by the body's own pmean, so unchecked is safe (the
        # ulysses precedent, parallel/ring_attention.py)
        local = compile_step(
            "ParallelWrapper.local_sgd_step", local_step, mesh=mesh,
            rule_set=self._rule_label(),
            in_specs=(stacked, stacked, stacked, stacked, stacked, repl,
                      repl),
            out_specs=(stacked, stacked, stacked, repl),
            strategy="shard_map", check_vma=False,
            cache_key=self._traced_policy, conf=net.conf)

        def average(params, upd, states):
            from deeplearning4j_tpu import common

            def mean_bcast(a):
                # cross-replica averaging follows the policy's grad-accum
                # dtype when that widens the leaf (bf16 replicas average in
                # f32); already-wide leaves average in their own dtype
                wide = common.accum_dtype(a.dtype)
                m = jnp.mean(a.astype(wide) if wide is not None else a,
                             axis=0, keepdims=True)
                return jnp.broadcast_to(m.astype(a.dtype), a.shape)
            avg = jax.tree_util.tree_map(mean_bcast, params)
            if self.average_updaters:
                upd = jax.tree_util.tree_map(mean_bcast, upd)
            # model state (batchnorm running stats) is averaged too — the reference
            # keeps BN stats inside params, which averageAndPropagate averages
            states = jax.tree_util.tree_map(mean_bcast, states)
            return avg, upd, states

        avg_fn = compile_step(
            "ParallelWrapper.average", average, mesh=mesh,
            rule_set=self._rule_label(), strategy="jit",
            cache_key=self._traced_policy, conf=net.conf)
        return local, avg_fn

    def _fit_local_sgd(self, iterator, epochs: int) -> None:
        net = self.model
        D = self.n_workers
        self._drop_stale_programs()
        if self._local_step is None:
            self._local_step, self._avg_fn = self._make_local_sgd_fns()
        stack = functools.partial(
            jax.tree_util.tree_map,
            lambda a: jnp.broadcast_to(a[None], (D,) + a.shape))
        sharding = _named_sharding(self.mesh, P("data"))
        params = jax.device_put(stack(net.params_list), sharding) \
            if jax.tree_util.tree_leaves(net.params_list) else net.params_list
        states = stack(net.state_list)
        upd = stack(net.updater_state)
        batch_sh = _named_sharding(self.mesh, P("data"))
        from deeplearning4j_tpu.nn.graph_network import (
            ComputationGraph, _coerce_graph_batch)

        is_graph = isinstance(net, ComputationGraph)
        # each psum-mean resync moves ~per-replica param bytes across the ring
        avg_bytes = _collective_bytes.labels(op="parameter_average",
                                             site="wrapper_local_sgd")
        param_bytes = _tree_nbytes(net.params_list)

        def stage(ds):
            # producer thread: sharded non-blocking transfer of the next
            # batch while the current local step runs
            if is_graph:
                xs, ys, _, _ = _coerce_graph_batch(ds)
                x = [jax.device_put(a, batch_sh) for a in xs]
                y = [jax.device_put(a, batch_sh) for a in ys]
                return x, y, 0
            bs = int(np.shape(ds.features)[0])
            return (jax.device_put(ds.features, batch_sh),
                    jax.device_put(ds.labels, batch_sh), bs)

        from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher

        since_avg = 0
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            pf = DevicePrefetcher(iterator, stage, depth=self.prefetch,
                                  path="wrapper_local_sgd",
                                  wait_series=_t_staging)
            for x, y, bs in pf:
                if not is_graph:
                    net.last_batch_size = bs
                t0 = _time.perf_counter()
                params, states, upd, loss = self._local_step(
                    params, states, upd, x, y, net._next_rng(),
                    jnp.int32(net.iteration))
                dt = _time.perf_counter() - t0
                _t_dispatch.observe(dt)
                _compile_tracker().note_step(fn="ParallelWrapper.local_step")
                _flight_recorder().record(
                    "step", path="ParallelWrapper.local_step",
                    it=net.iteration, batch=bs, dispatch_s=dt)
                net.score_value = loss  # synced lazily (LazyScore)
                net.iteration += 1
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, upd, states = self._avg_fn(params, upd, states)
                    avg_bytes.inc(param_bytes)
                    since_avg = 0
                with _t_listeners.time():
                    for listener in net.listeners:
                        listener.iteration_done(net, net.iteration)
                _wd_beat(net.iteration)
        # final sync + unstack back into the model
        params, upd, states = self._avg_fn(params, upd, states)
        unstack = functools.partial(jax.tree_util.tree_map, lambda a: a[0])
        net.params_list = unstack(params)
        net.state_list = unstack(states)
        net.updater_state = unstack(upd)
