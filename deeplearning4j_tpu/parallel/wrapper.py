"""ParallelWrapper: data-parallel training over a device mesh.

Reference: deeplearning4j-scaleout ParallelWrapper.java:44 — clones the model into one
trainer thread per device, round-robin feeds minibatches, averages params every
``averaging_frequency`` iterations via Nd4j.averageAndPropagate (:179) and optionally
averages updater state (:198-212).

TPU-native redesign — no threads, no clones, no explicit averaging transport:

* averaging_frequency == 1 (synchronous DP): ONE jit-compiled train step whose batch
  input is sharded over the 'data' mesh axis and whose params are replicated. The loss
  is the global-batch mean, so autodiff's gradients are automatically all-reduced by
  XLA (psum over ICI) — bitwise the same math as lockstep parameter averaging every
  iteration, with the collective fused into the step.

* averaging_frequency == N > 1 (local SGD, the reference's actual semantics): params
  carry a leading per-replica axis sharded over 'data'; a shard_map train step updates
  each replica locally from its shard of the batch, and every N iterations a psum-mean
  resynchronizes params (and optionally updater state) across replicas.

The same wrapper covers the Spark ParameterAveragingTrainingMaster use-case
(SURVEY.md §2.4): multi-host, the mesh just spans hosts via jax.distributed and the
collectives ride DCN.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh


class ParallelWrapperBuilder:
    """Mirrors reference ParallelWrapper.Builder (:483+)."""

    def __init__(self, model):
        self._model = model
        self._workers: Optional[int] = None
        self._prefetch = 2
        self._avg_freq = 1
        self._average_updaters = True
        self._report_score = False
        self._mesh: Optional[Mesh] = None

    def workers(self, n: int) -> "ParallelWrapperBuilder":
        self._workers = n
        return self

    def prefetch_buffer(self, n: int) -> "ParallelWrapperBuilder":
        self._prefetch = n
        return self

    def averaging_frequency(self, n: int) -> "ParallelWrapperBuilder":
        self._avg_freq = max(1, n)
        return self

    def average_updaters(self, flag: bool) -> "ParallelWrapperBuilder":
        self._average_updaters = flag
        return self

    def report_score_after_averaging(self, flag: bool) -> "ParallelWrapperBuilder":
        self._report_score = flag
        return self

    def mesh(self, mesh: Mesh) -> "ParallelWrapperBuilder":
        self._mesh = mesh
        return self

    def build(self) -> "ParallelWrapper":
        return ParallelWrapper(self._model, workers=self._workers,
                               prefetch=self._prefetch,
                               averaging_frequency=self._avg_freq,
                               average_updaters=self._average_updaters,
                               report_score=self._report_score, mesh=self._mesh)


class ParallelWrapper:
    def __init__(self, model, workers: Optional[int] = None, prefetch: int = 2,
                 averaging_frequency: int = 1, average_updaters: bool = True,
                 report_score: bool = False, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh or data_parallel_mesh(workers)
        self.n_workers = self.mesh.shape["data"]
        self.prefetch = prefetch
        self.averaging_frequency = averaging_frequency
        self.average_updaters = average_updaters
        self.report_score = report_score
        self._sync_step = None
        self._local_step = None
        self._avg_fn = None
        self._local = None  # stacked per-replica (params, states, upd) for local-SGD

    @staticmethod
    def builder(model) -> ParallelWrapperBuilder:
        return ParallelWrapperBuilder(model)

    # ------------------------------------------------------------------ public API
    def fit(self, iterator, epochs: int = 1) -> None:
        """Reference fit(DataSetIterator):322. Batches are sharded over the mesh;
        each global batch must be divisible by the number of workers."""
        if self.prefetch:
            iterator = AsyncDataSetIterator(iterator, queue_size=self.prefetch)
        if self.averaging_frequency == 1:
            self._fit_sync(iterator, epochs)
        else:
            self._fit_local_sgd(iterator, epochs)

    # ------------------------------------------------------- synchronous DP (freq=1)
    def _make_sync_step(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, make_train_step

        net = self.model
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        batch_sh = NamedSharding(mesh, P("data"))
        if isinstance(net, MultiLayerNetwork):
            base = make_train_step(net.conf)
        else:
            from deeplearning4j_tpu.nn.graph_network import make_graph_train_step
            base = make_graph_train_step(net.conf)

        def step(params, states, upd, x, y, rng, it):
            return base(params, states, upd, x, y, rng, it)

        return jax.jit(
            step,
            in_shardings=(repl, repl, repl, batch_sh, batch_sh, repl, repl),
            out_shardings=(repl, repl, repl, repl),
        )

    def _fit_sync(self, iterator, epochs: int) -> None:
        net = self.model
        if self._sync_step is None:
            self._sync_step = self._make_sync_step()
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph

        is_graph = isinstance(net, ComputationGraph)
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                if is_graph:
                    x = [jnp.asarray(f) for f in ([ds.features] if not isinstance(ds.features, list) else ds.features)]
                    y = [jnp.asarray(l) for l in ([ds.labels] if not isinstance(ds.labels, list) else ds.labels)]
                else:
                    x, y = jnp.asarray(ds.features), jnp.asarray(ds.labels)
                (net.params_list, net.state_list, net.updater_state, loss) = \
                    self._sync_step(net.params_list, net.state_list,
                                    net.updater_state, x, y, net._next_rng(),
                                    jnp.int32(net.iteration))
                net.score_value = float(loss)
                net.iteration += 1
                for listener in net.listeners:
                    listener.iteration_done(net, net.iteration)

    # --------------------------------------------------- local SGD (freq=N>1)
    def _make_local_sgd_fns(self):
        """shard_map local step over stacked per-replica params + psum-mean averager
        (reference averaging loop ParallelWrapper.java:179-212)."""
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph, make_graph_train_step
        from deeplearning4j_tpu.nn.multilayer import make_train_step

        net = self.model
        mesh = self.mesh
        if isinstance(net, ComputationGraph):
            if len(net.conf.network_inputs) != 1 or len(net.conf.network_outputs) != 1:
                raise NotImplementedError(
                    "local-SGD averaging supports single-input/single-output "
                    "ComputationGraphs; use averaging_frequency=1 for multi-IO graphs")
            graph_base = make_graph_train_step(net.conf)
            base = lambda p, s, u, x, y, r, it: graph_base(p, s, u, [x], [y], r, it)
        else:
            base = make_train_step(net.conf)
        stacked = P("data")
        repl = P()

        def local_step(params, states, upd, x, y, rng, it):
            # inside shard_map: leading axis is this replica's slice (size 1); drop it
            sq = functools.partial(jax.tree_util.tree_map, lambda a: a[0])
            ex = functools.partial(jax.tree_util.tree_map, lambda a: a[None])
            p, s, u = sq(params), sq(states), sq(upd)
            rng_local = jax.random.fold_in(rng, jax.lax.axis_index("data"))
            p2, s2, u2, loss = base(p, s, u, x, y, rng_local, it)
            return ex(p2), ex(s2), ex(u2), jax.lax.pmean(loss, "data")

        local = jax.jit(shard_map(
            local_step, mesh=mesh,
            in_specs=(stacked, stacked, stacked, stacked, stacked, repl, repl),
            out_specs=(stacked, stacked, stacked, repl),
        ))

        def average(params, upd, states):
            mean_bcast = lambda a: jnp.broadcast_to(
                jnp.mean(a, axis=0, keepdims=True), a.shape)
            avg = jax.tree_util.tree_map(mean_bcast, params)
            if self.average_updaters:
                upd = jax.tree_util.tree_map(mean_bcast, upd)
            # model state (batchnorm running stats) is averaged too — the reference
            # keeps BN stats inside params, which averageAndPropagate averages
            states = jax.tree_util.tree_map(mean_bcast, states)
            return avg, upd, states

        avg_fn = jax.jit(average)
        return local, avg_fn

    def _fit_local_sgd(self, iterator, epochs: int) -> None:
        net = self.model
        D = self.n_workers
        if self._local_step is None:
            self._local_step, self._avg_fn = self._make_local_sgd_fns()
        stack = functools.partial(
            jax.tree_util.tree_map,
            lambda a: jnp.broadcast_to(a[None], (D,) + a.shape))
        sharding = NamedSharding(self.mesh, P("data"))
        params = jax.device_put(stack(net.params_list), sharding) \
            if jax.tree_util.tree_leaves(net.params_list) else net.params_list
        states = stack(net.state_list)
        upd = stack(net.updater_state)
        batch_sh = NamedSharding(self.mesh, P("data"))
        since_avg = 0
        for _ in range(epochs):
            if hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                x = jax.device_put(jnp.asarray(ds.features), batch_sh)
                y = jax.device_put(jnp.asarray(ds.labels), batch_sh)
                params, states, upd, loss = self._local_step(
                    params, states, upd, x, y, net._next_rng(),
                    jnp.int32(net.iteration))
                net.score_value = float(loss)
                net.iteration += 1
                since_avg += 1
                if since_avg >= self.averaging_frequency:
                    params, upd, states = self._avg_fn(params, upd, states)
                    since_avg = 0
                for listener in net.listeners:
                    listener.iteration_done(net, net.iteration)
        # final sync + unstack back into the model
        params, upd, states = self._avg_fn(params, upd, states)
        unstack = functools.partial(jax.tree_util.tree_map, lambda a: a[0])
        net.params_list = unstack(params)
        net.state_list = unstack(states)
        net.updater_state = unstack(upd)
