"""Partition-rule engine: named param trees -> regex rules -> PartitionSpecs.

THE one place sharding layouts come from (ROADMAP open item 3). Every
parallel fit path used to hand-wire its own ``NamedSharding``s, so tensor
parallelism and ZeRO-style parameter/optimizer sharding were new code paths
instead of config choices. Here the GSPMD idiom (match_partition_rules over
a ``/``-joined named tree, SNIPPETS.md [1]) centralizes it:

  1. ``named_tree_map`` walks any pytree with ``/``-joined path strings;
     model trees (MultiLayerNetwork ``params_list`` / ComputationGraph
     params dicts, and the updater state mirroring them) get their top
     component enriched with the layer class name, so a rule can target
     ``0.DenseLayer/W`` or ``ff.TransformerBlock/Wqkv`` — and, because
     optimizer-state leaves extend the same path (``.../W/m``), one rule
     shards a parameter and its moments alike.
  2. ``match_partition_rules(rules, tree, ...)`` maps ``(regex, spec)``
     rules, first match wins, onto a PartitionSpec pytree. Scalars and tiny
     vectors fall through to replicated; an unmatched non-scalar leaf is a
     hard ``PartitionRuleError`` (silent replication is how a "sharded" run
     quietly stops scaling). A matched leaf whose dims don't divide the mesh
     axis demotes to replicated — the same forgiving behavior the old
     per-path constructors had.
  3. Built-in rule sets: ``dp`` (replicate params, shard the batch),
     ``dp_tp`` (Megatron column/row splits for dense/attention/MoE
     weights), ``zero3`` (params + optimizer state sharded over the data
     axis, all-gathered per layer by GSPMD from the sharding constraints).

Rank-polymorphic rule values ``Col``/``Row``/``FirstDivisible`` let one rule
cover a 2-D dense W, a 4-D conv HWIO W, and a 3-D MoE expert stack: ``Col``
shards the last (output) dim, ``Row`` the second-to-last (input) dim,
``FirstDivisible`` the first dim the axis divides (the ZeRO scan).

Specs are layout *hints*: XLA GSPMD inserts the collectives the layout
implies, so numerics are identical across rule sets — the equivalence tests
pin that. Construction of raw ``NamedSharding``/``PartitionSpec`` outside
this module and ``compile_seam.py`` is flagged by the ``adhoc-sharding``
lint rule; other modules import :data:`pspec` for trace-level specs and use
:func:`named_sharding`/:func:`tree_shardings`/:func:`device_put` for
placement.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deeplearning4j_tpu.observability.metrics import (
    global_registry as _obs_registry, tree_nbytes as _tree_nbytes,
)
from deeplearning4j_tpu.observability.names import (
    SHARDED_PARAM_BYTES_PER_DEVICE, SHARDING_SPEC_TOTAL,
)

#: the sanctioned spec constructor for trace-level code (shard_map in_specs,
#: batch specs). A PartitionSpec is device-free data; placement (NamedSharding)
#: must go through the helpers below so layouts stay greppable in one place.
pspec = PartitionSpec

#: 1-D leaves below this many elements replicate regardless of rules —
#: mirrors the old ``param_pspec`` bias floor (shape[0] >= 8): sharding an
#: 8-float bias buys nothing and costs a collective.
TINY_VECTOR = 8


class PartitionRuleError(ValueError):
    """A non-scalar leaf matched no rule. Raised, not defaulted: a silently
    replicated 2 GB embedding is a perf bug that looks like a working run."""


# ------------------------------------------------------------- rule values
class FirstDivisible:
    """Shard the first dim the mesh axis divides; replicate if none (the
    ZeRO parameter/optimizer scan — old ``_tree_shardings`` behavior)."""

    def __init__(self, axis: str = "data"):
        self.axis = axis

    def __repr__(self):
        return f"FirstDivisible({self.axis!r})"


class Col:
    """Megatron column parallelism: shard the LAST (output) dim. Covers 2-D
    dense W -> P(None, axis), conv HWIO W -> P(None, None, None, axis),
    MoE expert stacks [E, F, H] -> P(None, None, axis), 1-D bias -> P(axis)."""

    def __init__(self, axis: str = "model"):
        self.axis = axis

    def __repr__(self):
        return f"Col({self.axis!r})"


class Row:
    """Megatron row parallelism: shard the SECOND-TO-LAST (input) dim.
    1-D leaves replicate (a row-split layer's bias must be replicated)."""

    def __init__(self, axis: str = "model"):
        self.axis = axis

    def __repr__(self):
        return f"Row({self.axis!r})"


# ---------------------------------------------------------------- tree walk
def _is_container(x) -> bool:
    return isinstance(x, (dict, list, tuple)) and not isinstance(x, PartitionSpec)


def _path_str(key_path, sep: str) -> str:
    tu = jax.tree_util
    parts = []
    for k in key_path:
        if isinstance(k, tu.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, tu.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, tu.GetAttrKey):
            parts.append(str(k.name))
        elif isinstance(k, tu.FlattenedIndexKey):
            parts.append(str(k.key))
        else:  # future key types: their str() is already path-like
            parts.append(str(k).strip("[].'\""))
    return sep.join(parts)


def named_tree_map(f: Callable[..., Any], tree, *rest, sep: str = "/",
                   top_names: Optional[dict] = None, is_leaf=None):
    """``jax.tree_util.tree_map`` whose function receives the ``sep``-joined
    path as its first argument: ``f(path, leaf, *rest_leaves)``.

    ``top_names`` optionally rewrites the first path component (used to
    enrich layer indices / vertex names with layer class names)."""
    def g(key_path, leaf, *r):
        path = _path_str(key_path, sep)
        if top_names:
            head, _, tail = path.partition(sep)
            head = top_names.get(head, head)
            path = head + (sep + tail if tail else "")
        return f(path, leaf, *r)

    return jax.tree_util.tree_map_with_path(g, tree, *rest, is_leaf=is_leaf)


def model_top_names(tree, conf) -> dict:
    """Map a model tree's top-level components to layer-type-enriched names:
    list index ``0`` -> ``0.DenseLayer``, vertex ``ff`` -> ``ff.TransformerBlock``.
    Works for params, grads, and updater state alike — they share structure."""
    if conf is None:
        return {}
    layers = getattr(conf, "layers", None)
    if isinstance(tree, (list, tuple)) and layers:
        return {str(i): f"{i}.{type(l).__name__}" for i, l in enumerate(layers)}
    vertices = getattr(conf, "vertices", None)
    if isinstance(tree, dict) and vertices:
        out = {}
        for name in tree:
            layer = getattr(vertices.get(name), "layer", None)
            out[name] = f"{name}.{type(layer).__name__}" if layer is not None \
                else name
        return out
    return {}


# ------------------------------------------------------------- rule matching
def _axis_factor(mesh: Optional[Mesh], axis) -> Optional[int]:
    """Product of mesh sizes for a spec axis entry (name or tuple of names);
    None if any name is absent from the mesh."""
    f = 1
    for a in (axis if isinstance(axis, tuple) else (axis,)):
        if mesh is None or a not in mesh.shape:
            return None
        f *= mesh.shape[a]
    return f


def _resolve(value, shape: Sequence[int], mesh: Optional[Mesh]) -> PartitionSpec:
    """Turn a rule value into a concrete spec for ``shape``, demoting to
    replicated when the mesh axis is absent or doesn't divide the dim."""
    if isinstance(value, FirstDivisible):
        f = _axis_factor(mesh, value.axis)
        if f is not None:
            for d, n in enumerate(shape):
                if n % f == 0:
                    return PartitionSpec(*([None] * d), value.axis)
        return PartitionSpec()
    if isinstance(value, Col):
        f = _axis_factor(mesh, value.axis)
        if f is not None and shape and shape[-1] % f == 0:
            return PartitionSpec(*([None] * (len(shape) - 1)), value.axis)
        return PartitionSpec()
    if isinstance(value, Row):
        f = _axis_factor(mesh, value.axis)
        if f is not None and len(shape) >= 2 and shape[-2] % f == 0:
            return PartitionSpec(*([None] * (len(shape) - 2)), value.axis, None)
        return PartitionSpec()
    if isinstance(value, PartitionSpec):
        if len(value) > len(shape):
            return PartitionSpec()
        for d, ax in enumerate(value):
            if ax is None:
                continue
            f = _axis_factor(mesh, ax)
            if f is None or shape[d] % f:
                return PartitionSpec()
        return value
    raise TypeError(f"rule value {value!r} is not a PartitionSpec/"
                    f"Col/Row/FirstDivisible")


def match_partition_rules(rules: Iterable[Tuple[str, Any]], tree, *,
                          mesh: Optional[Mesh] = None, conf=None,
                          sep: str = "/") -> Any:
    """Map ``(regex, spec)`` rules onto ``tree`` -> PartitionSpec pytree.

    First match wins (``re.search``, so rules are unanchored — write
    ``/W(/|$)`` to hit both a param and its optimizer moments ``/W/m``).
    Scalars / size-1 / tiny 1-D leaves replicate without consulting rules;
    an unmatched non-scalar leaf raises :class:`PartitionRuleError`.
    """
    rules = [(re.compile(pat), val) for pat, val in rules]
    top = model_top_names(tree, conf)

    def spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        size = 1
        for n in shape:
            size *= n
        if not shape or size <= 1 or (len(shape) == 1 and size < TINY_VECTOR):
            return PartitionSpec()
        for pat, val in rules:
            if pat.search(path):
                return _resolve(val, shape, mesh)
        raise PartitionRuleError(
            f"no partition rule matches leaf {path!r} with shape {shape}; "
            f"add a rule (or an explicit '.*' -> P() catch-all) — silent "
            f"replication is not a default")

    return named_tree_map(spec_for, tree, sep=sep, top_names=top)


# -------------------------------------------------------------- rule sets
def dp_rules() -> list:
    """Pure data parallelism: every parameter replicated; the batch dim of
    activations is sharded by the caller's batch spec, not by param rules."""
    return [(r".*", PartitionSpec())]


def dp_tp_rules(model_axis: str = "model") -> list:
    """Megatron-style dp x tp. Column-split the up-projections (fused QKV,
    MLP/expert W1, dense/conv/LSTM output dims) and their biases; row-split
    the down-projections (attention Wo, MLP/expert W2) whose biases and the
    norm/gate params stay replicated. Indivisible dims demote to replicated
    per-leaf, so a mixed net (e.g. a 3-wide output head) still compiles."""
    return [
        (r"/Wqkv(/|$)", Col(model_axis)),            # fused QKV: head split
        (r"/Wo(/|$)", Row(model_axis)),              # attn out-proj: row
        (r"/W1(/|$)", Col(model_axis)),              # MLP / expert up: column
        (r"/W2(/|$)", Row(model_axis)),              # MLP / expert down: row
        (r"/b1(/|$)", Col(model_axis)),              # bias of the column split
        (r"/(W|RW|FW|FRW|BW|BRW)(/|$)", Col(model_axis)),  # dense/conv/LSTM
        (r"/(b|Fb|Bb)(/|$)", Col(model_axis)),       # 1-D biases (TINY floor)
        (r".*", PartitionSpec()),                    # norms, gates, the rest
    ]


def zero3_rules(data_axis: str = "data") -> list:
    """ZeRO-3: every parameter and optimizer-state leaf sharded over the
    data axis on its first divisible dim; GSPMD all-gathers per layer at use
    sites from the sharding constraints (no manual gather code)."""
    return [(r".*", FirstDivisible(data_axis))]


RULE_SETS = {"dp": dp_rules, "dp_tp": dp_tp_rules, "zero3": zero3_rules}


def rules_for(name: str, **kwargs) -> list:
    try:
        return RULE_SETS[name](**kwargs)
    except KeyError:
        raise ValueError(f"unknown rule set {name!r}; have {sorted(RULE_SETS)}")


# ----------------------------------------------------------- placement API
def named_sharding(mesh: Mesh, spec: Optional[PartitionSpec] = None) -> NamedSharding:
    """THE NamedSharding constructor — call sites outside the engine use this
    (keeps every placement greppable; enforced by the adhoc-sharding rule)."""
    return NamedSharding(mesh, PartitionSpec() if spec is None else spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return named_sharding(mesh, PartitionSpec())


def tree_shardings(mesh: Mesh, spec_tree):
    """PartitionSpec pytree (or prefix) -> NamedSharding pytree (or prefix).
    ``None`` entries pass through (jit: inherit the committed placement)."""
    if spec_tree is None:
        return None
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), spec_tree)


def device_put(tree, mesh: Mesh, spec_tree):
    """Place a host/device tree per a spec pytree (or a single prefix spec)."""
    return jax.device_put(tree, tree_shardings(mesh, spec_tree))


def batch_spec(mesh: Mesh, n: int, axis: str = "data") -> PartitionSpec:
    """Leading-batch-axis spec for staging a batch of ``n`` rows: split on
    ``axis`` when the mesh divides ``n`` evenly, replicated otherwise (odd
    tail buckets must still dispatch, just without the data split)."""
    f = _axis_factor(mesh, axis)
    if f and f > 1 and n % f == 0:
        return PartitionSpec(axis)
    return PartitionSpec()


# --------------------------------------------------------------- telemetry
_spec_counter = _obs_registry().counter(
    SHARDING_SPEC_TOTAL,
    "partition-rule engine spec decisions, one count per leaf per "
    "compiled step, by rule set and resolved spec")
_param_bytes_gauge = _obs_registry().gauge(
    SHARDED_PARAM_BYTES_PER_DEVICE,
    "per-device bytes of the parameter tree under the resolved specs — "
    "zero3 should read ~1/N of the replicated figure")


def _spec_label(spec: PartitionSpec) -> str:
    return "P(" + ",".join(str(a) for a in spec) + ")"


def shard_factor(mesh: Mesh, spec: PartitionSpec) -> int:
    """How many ways the spec splits one array across the mesh."""
    f = 1
    for ax in spec:
        if ax is None:
            continue
        f *= _axis_factor(mesh, ax) or 1
    return f


def per_device_bytes(tree, spec_tree, mesh: Mesh) -> int:
    """Bytes of ``tree`` resident per device under ``spec_tree`` (a spec
    pytree matching ``tree``, or a single prefix spec for the whole tree)."""
    if isinstance(spec_tree, PartitionSpec):
        prefix = spec_tree
        spec_tree = jax.tree_util.tree_map(lambda _: prefix, tree)
    total = 0.0
    leaves = jax.tree_util.tree_leaves(
        named_tree_map(lambda _p, leaf, spec:
                       _tree_nbytes(leaf) / shard_factor(mesh, spec),
                       tree, spec_tree))
    for b in leaves:
        total += b
    return int(total)


def record_specs(rule_set: str, *spec_trees) -> None:
    for tree in spec_trees:
        for s in jax.tree_util.tree_leaves(tree):
            if isinstance(s, PartitionSpec):
                _spec_counter.labels(rule_set=rule_set,
                                     spec=_spec_label(s)).inc()


def record_param_bytes(rule_set: str, tree, spec_tree, mesh: Mesh) -> int:
    b = per_device_bytes(tree, spec_tree, mesh)
    _param_bytes_gauge.labels(rule_set=rule_set).set(b)
    return b
