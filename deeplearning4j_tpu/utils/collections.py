"""Counting/priority-queue utilities (berkeley-utils equivalents).

Reference: deeplearning4j-nn berkeley/*.java (SURVEY.md §2.1) — legacy
Berkeley NLP `Counter`, `PriorityQueue`, `Pair`, `Triple` used across the
reference. Python's stdlib covers most of this (collections.Counter, heapq,
tuples); this module provides the reference's richer Counter surface
(normalization, argmax, scaling) and a max-priority queue with the Berkeley
API shape, so ported call sites have a one-to-one target.
"""
from __future__ import annotations

import heapq
import itertools
from collections import Counter as _Counter
from typing import Dict, Generic, Iterator, List, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class Counter(Generic[K]):
    """reference berkeley/Counter.java: float-valued counts with
    normalization/argmax/scale."""

    def __init__(self):
        self._c: Dict[K, float] = {}

    def increment_count(self, key: K, amount: float = 1.0) -> None:
        self._c[key] = self._c.get(key, 0.0) + amount

    def set_count(self, key: K, count: float) -> None:
        self._c[key] = count

    def get_count(self, key: K) -> float:
        return self._c.get(key, 0.0)

    def total_count(self) -> float:
        return sum(self._c.values())

    def argmax(self) -> Optional[K]:
        return max(self._c, key=self._c.get) if self._c else None

    def max_count(self) -> float:
        return max(self._c.values()) if self._c else 0.0

    def normalize(self) -> None:
        total = self.total_count()
        if total:
            for k in self._c:
                self._c[k] /= total

    def scale(self, factor: float) -> None:
        for k in self._c:
            self._c[k] *= factor

    def remove_key(self, key: K) -> None:
        self._c.pop(key, None)

    def key_set(self) -> List[K]:
        return list(self._c)

    def is_empty(self) -> bool:
        return not self._c

    def __len__(self) -> int:
        return len(self._c)

    def __iter__(self) -> Iterator[K]:
        return iter(self._c)

    def items(self):
        return self._c.items()

    def to_collections_counter(self) -> _Counter:
        return _Counter(self._c)


class PriorityQueue(Generic[V]):
    """reference berkeley/PriorityQueue.java: MAX-priority queue with
    iterator-style next()/peek() (heapq is a min-heap; priorities negate)."""

    def __init__(self):
        self._heap: List[Tuple[float, int, V]] = []
        self._tie = itertools.count()

    def put(self, item: V, priority: float) -> None:
        heapq.heappush(self._heap, (-priority, next(self._tie), item))

    # Berkeley API name
    add = put

    def next(self) -> V:
        return heapq.heappop(self._heap)[2]

    def peek(self) -> V:
        return self._heap[0][2]

    def get_priority(self) -> float:
        return -self._heap[0][0]

    def has_next(self) -> bool:
        return bool(self._heap)

    def is_empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self) -> Iterator[V]:
        while self.has_next():
            yield self.next()


def pair(first, second) -> Tuple:
    """reference berkeley/Pair.java — a plain tuple in Python."""
    return (first, second)


def triple(first, second, third) -> Tuple:
    """reference berkeley/Triple.java."""
    return (first, second, third)
