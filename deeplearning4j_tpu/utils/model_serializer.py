"""Model serialization: single-file zip checkpoint with config + params + updater state.

Reference: util/ModelSerializer.java:41-118 — zip container with configuration.json,
coefficients.bin, updaterState.bin, normalizer.bin. Same container layout here (npz
streams instead of raw ND4J buffers), so training resumes bit-identically: optimizer
state is saved alongside parameters, and batchnorm running stats ride in a state entry
(the reference keeps them inside params; here they are a separate pytree).

Also provides ModelGuesser-style load-anything (reference core util/ModelGuesser.java).
"""
from __future__ import annotations

import io
import json
import zipfile

import jax
import jax.numpy as jnp
import numpy as np

CONFIG_ENTRY = "configuration.json"
PARAMS_ENTRY = "coefficients.npz"
UPDATER_ENTRY = "updaterState.npz"
MODEL_STATE_ENTRY = "modelState.npz"
NORMALIZER_ENTRY = "normalizer.npz"
META_ENTRY = "meta.json"


def _tree_to_npz_bytes(tree) -> bytes:
    """Serialize a pytree of arrays to npz with path-encoded keys."""
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    arrays = {}
    for path, leaf in leaves_with_paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arrays[key] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_bytes_to_tree(template, data: bytes):
    """Restore a pytree from npz using ``template`` for structure."""
    npz = np.load(io.BytesIO(data))
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        arr = npz[key]
        leaves.append(jnp.asarray(arr, leaf.dtype if hasattr(leaf, "dtype") else None))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def write_model(net, path: str, save_updater: bool = True,
                normalizer=None) -> None:
    """Write a model zip (reference ModelSerializer.writeModel:55-118)."""
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(CONFIG_ENTRY, net.conf.to_json())
        zf.writestr(PARAMS_ENTRY, _tree_to_npz_bytes(net.params_list))
        zf.writestr(MODEL_STATE_ENTRY, _tree_to_npz_bytes(net.state_list))
        if save_updater and net.updater_state is not None:
            zf.writestr(UPDATER_ENTRY, _tree_to_npz_bytes(net.updater_state))
        if normalizer is not None:
            zf.writestr(NORMALIZER_ENTRY, _tree_to_npz_bytes(normalizer.to_arrays()))
        meta = {"iteration": net.iteration, "epoch": getattr(net, "epoch", 0),
                "model_type": type(net).__name__,
                "framework": "deeplearning4j_tpu", "format_version": 1}
        zf.writestr(META_ENTRY, json.dumps(meta))


def restore_multi_layer_network(path: str, load_updater: bool = True):
    """Reference ModelSerializer.restoreMultiLayerNetwork."""
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path) as zf:
        conf = MultiLayerConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
        net = MultiLayerNetwork(conf)
        net.init()
        net.params_list = _npz_bytes_to_tree(net.params_list, zf.read(PARAMS_ENTRY))
        if MODEL_STATE_ENTRY in zf.namelist():
            net.state_list = _npz_bytes_to_tree(net.state_list,
                                                zf.read(MODEL_STATE_ENTRY))
        if load_updater and UPDATER_ENTRY in zf.namelist():
            net.updater_state = _npz_bytes_to_tree(net.updater_state,
                                                   zf.read(UPDATER_ENTRY))
        if META_ENTRY in zf.namelist():
            meta = json.loads(zf.read(META_ENTRY).decode())
            net.iteration = meta.get("iteration", 0)
            net.epoch = meta.get("epoch", 0)
    return net


def restore_computation_graph(path: str, load_updater: bool = True):
    """Reference ModelSerializer.restoreComputationGraph."""
    from deeplearning4j_tpu.nn.conf.graphconf import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph_network import ComputationGraph

    with zipfile.ZipFile(path) as zf:
        conf = ComputationGraphConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
        net = ComputationGraph(conf)
        net.init()
        net.params_list = _npz_bytes_to_tree(net.params_list, zf.read(PARAMS_ENTRY))
        if MODEL_STATE_ENTRY in zf.namelist():
            net.state_list = _npz_bytes_to_tree(net.state_list,
                                                zf.read(MODEL_STATE_ENTRY))
        if load_updater and UPDATER_ENTRY in zf.namelist():
            net.updater_state = _npz_bytes_to_tree(net.updater_state,
                                                   zf.read(UPDATER_ENTRY))
        if META_ENTRY in zf.namelist():
            meta = json.loads(zf.read(META_ENTRY).decode())
            net.iteration = meta.get("iteration", 0)
            net.epoch = meta.get("epoch", 0)
    return net


def restore_normalizer(path: str):
    from deeplearning4j_tpu.datasets.dataset import NormalizerStandardize

    with zipfile.ZipFile(path) as zf:
        if NORMALIZER_ENTRY not in zf.namelist():
            return None
        npz = np.load(io.BytesIO(zf.read(NORMALIZER_ENTRY)))
        return NormalizerStandardize.from_arrays({k: npz[k] for k in npz.files})


def guess_model(path: str):
    """Load whichever model type the file contains (reference util/ModelGuesser.java)."""
    with zipfile.ZipFile(path) as zf:
        if META_ENTRY in zf.namelist():
            meta = json.loads(zf.read(META_ENTRY).decode())
            if meta.get("model_type") == "ComputationGraph":
                return restore_computation_graph(path)
            return restore_multi_layer_network(path)
        config = json.loads(zf.read(CONFIG_ENTRY).decode())
        if config.get("@type") == "ComputationGraphConfiguration":
            return restore_computation_graph(path)
        return restore_multi_layer_network(path)
