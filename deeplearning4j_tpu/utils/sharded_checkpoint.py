"""Sharded (orbax) checkpointing for mesh-distributed training state.

The zip container (`utils/model_serializer`, reference
util/ModelSerializer.java) gathers everything to one host — fine for
single-chip models, wrong for mesh-sharded ones: a TP/FSDP-sharded param
tree would be all-gathered through the host on every save. This module is
the TPU-native alternative (SURVEY.md §5 checkpoint/resume: "orbax-style
checkpoint of {config-json, params, opt-state, normalizer}"): each host
writes only its addressable shards via orbax/TensorStore, restore places
shards directly onto the target sharding, and the model config travels
alongside as JSON so a checkpoint is self-describing. Works multi-host
(every process calls save/restore collectively) and on the single-process
virtual mesh the test suite uses.
"""
from __future__ import annotations

import json
import os
from typing import Optional

import jax

_PARAMS = "params"
_UPDATER = "updater"
_STATES = "states"
_CONFIG_FILE = "config.json"
_META_FILE = "meta.json"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _async_checkpointer():
    import orbax.checkpoint as ocp

    return ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())


def _snapshot_sidecar(net, step: Optional[int]) -> dict:
    """Capture the sidecar payload at save() time — the async writer flushes
    it later, by which point ``net`` may have trained further."""
    return {"config": net.conf.to_json(),
            "meta": {"iteration": int(getattr(net, "iteration", 0)),
                     "epoch": int(getattr(net, "epoch", 0)),
                     "step": step,
                     "network_type": type(net).__name__}}


def _write_sidecar_payload(directory: str, payload: dict) -> None:
    """Config + bookkeeping JSON beside the array state — the ONE writer
    shared by sync and async saves so the schema can never diverge. The
    sidecar doubles as the COMMIT MARKER: restore_sharded refuses array
    state that lacks it, so it must only be written once the array state is
    known to be on disk. (Tiny host-side files; process 0 writes.)"""
    if jax.process_index() != 0:
        return
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, _CONFIG_FILE), "w") as f:
        f.write(payload["config"])
    with open(os.path.join(directory, _META_FILE), "w") as f:
        json.dump(payload["meta"], f)


def _write_sidecar(directory: str, net, step: Optional[int]) -> None:
    _write_sidecar_payload(directory, _snapshot_sidecar(net, step))


def _uncommit_sidecar(directory: str) -> None:
    """Remove a previous save's commit marker before new array state starts
    writing, so a crash mid-write can't leave a stale sidecar endorsing a
    half-written state directory."""
    if jax.process_index() != 0:
        return
    for name in (_CONFIG_FILE, _META_FILE):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            os.remove(path)


def _clear_state_dir(directory: str) -> None:
    """orbax refuses to overwrite an existing checkpoint dir; rolling saves
    to one directory must clear the previous array state first."""
    import shutil

    state = os.path.join(directory, "state")
    if os.path.exists(state):
        shutil.rmtree(state)


def save_sharded(directory: str, net, *, step: Optional[int] = None) -> str:
    """Write a sharded checkpoint of the network's full training state.

    Each leaf keeps its current ``jax.sharding`` layout on disk, so no host
    gather happens for distributed params. Re-saving to the same directory
    replaces the previous state. Returns the directory.
    """
    directory = os.path.abspath(directory)
    _clear_state_dir(directory)
    ckpt = _checkpointer()
    tree = {_PARAMS: net.params_list, _STATES: net.state_list,
            _UPDATER: net.updater_state}
    ckpt.save(os.path.join(directory, "state"), tree)
    _write_sidecar(directory, net, step)
    return directory


class AsyncShardedSaver:
    """Non-blocking sharded saves: device buffers are snapshotted, then
    TensorStore writes proceed on background threads while training
    continues — the save no longer stalls the step loop (the same reason
    the reference runs checkpoint listeners off the hot path). One
    in-flight save at a time: a new ``save`` waits for the previous write
    to land (orbax AsyncCheckpointer semantics), and ``wait()`` must be
    called (or the object used as a context manager) before reading the
    checkpoint or exiting the process.

    Commit ordering: the config/meta sidecar is the checkpoint's commit
    marker, so it is written only AFTER ``wait_until_finished`` confirms the
    background array write landed — never alongside the in-flight write. A
    crash mid-save therefore leaves a state directory without a sidecar,
    which ``restore_sharded`` rejects as incomplete instead of restoring a
    torn checkpoint. The payload is still snapshotted at ``save()`` time, so
    the committed iteration/epoch match the arrays, not whatever the net
    trained on to while the write was in flight.
    """

    def __init__(self):
        self._ckpt = _async_checkpointer()
        self._pending: Optional[tuple[str, dict]] = None

    def save(self, directory: str, net, *, step: Optional[int] = None) -> str:
        directory = os.path.abspath(directory)
        # rolling saves to one dir: wait out any in-flight write (committing
        # its sidecar), then clear the previous state (orbax refuses to
        # overwrite) and uncommit so no stale sidecar endorses the new
        # partially-written state
        self._ckpt.wait_until_finished()
        self._flush_pending()
        _clear_state_dir(directory)
        _uncommit_sidecar(directory)
        tree = {_PARAMS: net.params_list, _STATES: net.state_list,
                _UPDATER: net.updater_state}
        self._ckpt.save(os.path.join(directory, "state"), tree)
        self._pending = (directory, _snapshot_sidecar(net, step))
        return directory

    def _flush_pending(self) -> None:
        """Commit the sidecar for a landed write (call only after
        ``wait_until_finished``)."""
        if self._pending is not None:
            pending_dir, payload = self._pending
            self._pending = None
            _write_sidecar_payload(pending_dir, payload)

    def wait(self) -> None:
        self._ckpt.wait_until_finished()
        self._flush_pending()

    def close(self) -> None:
        self.wait()
        self._ckpt.close()

    def __enter__(self) -> "AsyncShardedSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def restore_sharded(directory: str, net=None, *, shardings=None):
    """Restore a sharded checkpoint.

    ``net``: a constructed (possibly uninitialized) network to restore into;
    None rebuilds one from the stored config JSON. ``shardings``: optional
    pytree (or prefix) of `jax.sharding.Sharding` matching the params tree —
    leaves restore DIRECTLY onto those device placements (no host
    round-trip); None restores to the default device layout.
    """
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    # the sidecar is the commit marker (written only after the array write
    # landed — AsyncShardedSaver docstring): array state without it means a
    # save crashed mid-write and the checkpoint must not be trusted
    if (os.path.exists(os.path.join(directory, "state"))
            and not os.path.exists(os.path.join(directory, _META_FILE))):
        raise RuntimeError(
            f"checkpoint at {directory} has array state but no committed "
            f"sidecar ({_META_FILE}); an async save likely crashed before "
            "wait()/close() — refusing to restore an incomplete checkpoint")
    if net is None:
        with open(os.path.join(directory, _CONFIG_FILE)) as f:
            net = _net_from_config(f.read(), directory)
    if net.params_list is None:
        net.init()

    template = {_PARAMS: net.params_list, _STATES: net.state_list,
                _UPDATER: net.updater_state}
    if shardings is not None:
        restore_args = {
            _PARAMS: jax.tree_util.tree_map(
                lambda leaf, sh: ocp.ArrayRestoreArgs(sharding=sh),
                net.params_list, shardings),
            _STATES: jax.tree_util.tree_map(
                lambda leaf: ocp.RestoreArgs(), net.state_list),
            _UPDATER: jax.tree_util.tree_map(
                lambda leaf: ocp.RestoreArgs(), net.updater_state),
        }
        tree = _checkpointer().restore(os.path.join(directory, "state"),
                                       item=template,
                                       restore_args=restore_args)
    else:
        tree = _checkpointer().restore(os.path.join(directory, "state"),
                                       item=template)
    net.params_list = tree[_PARAMS]
    net.state_list = tree[_STATES]
    net.updater_state = tree[_UPDATER]
    meta_path = os.path.join(directory, _META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        net.iteration = int(meta.get("iteration", 0))
        net.epoch = int(meta.get("epoch", 0))
    return net


def _net_from_config(config_json: str, directory: str):
    with open(os.path.join(directory, _META_FILE)) as f:
        net_type = json.load(f).get("network_type", "MultiLayerNetwork")
    if net_type == "ComputationGraph":
        from deeplearning4j_tpu.nn.conf.graphconf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph_network import ComputationGraph

        return ComputationGraph(
            ComputationGraphConfiguration.from_json(config_json))
    from deeplearning4j_tpu.nn.conf.multilayer import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    return MultiLayerNetwork(MultiLayerConfiguration.from_json(config_json))
