"""EvaluationTools: standalone HTML reports for evaluation results.

Reference: deeplearning4j-core evaluation/EvaluationTools.java — exports ROC
charts (+ AUC) and evaluation summaries as self-contained HTML via the
ui-components renderer (SURVEY.md §2.2/§2.9).
"""
from __future__ import annotations

from deeplearning4j_tpu.ui.components import (
    ChartLine, ComponentTable, ComponentText, render_page,
)


def roc_chart(roc, name: str = "ROC") -> ChartLine:
    fpr, tpr = [], []
    for point in roc.get_roc_curve():
        fpr.append(float(point[0]))
        tpr.append(float(point[1]))
    chart = ChartLine(f"{name} (AUC = {roc.calculate_auc():.4f})",
                      x_label="false positive rate",
                      y_label="true positive rate")
    chart.add_series(name, fpr, tpr)
    chart.add_series("chance", [0.0, 1.0], [0.0, 1.0])
    return chart


def export_roc_charts_to_html_file(roc, path: str) -> None:
    """Reference EvaluationTools.exportRocChartsToHtmlFile(ROC, File)."""
    html = render_page("ROC report", roc_chart(roc))
    with open(path, "w") as f:
        f.write(html)


def export_roc_multi_class_to_html_file(roc_mc, path: str) -> None:
    """One chart per class + AUC summary table (reference
    exportRocChartsToHtmlFile(ROCMultiClass, File))."""
    charts = []
    rows = []
    for c in sorted(roc_mc.per_class):
        roc = roc_mc.per_class[c]
        charts.append(roc_chart(roc, name=f"class {c}"))
        rows.append([c, roc_mc.calculate_auc(c)])
    summary = ComponentTable(["class", "AUC"], rows, title="AUC per class")
    avg = ComponentText(
        f"average AUC: {roc_mc.calculate_average_auc():.4f}")
    with open(path, "w") as f:
        f.write(render_page("ROC (multi-class) report", summary, avg, *charts))


def export_evaluation_to_html_file(evaluation, path: str) -> None:
    """Confusion matrix + headline metrics as HTML."""
    cm = evaluation.confusion.matrix
    n = len(cm)
    table = ComponentTable(
        ["actual \\ predicted"] + [str(i) for i in range(n)],
        [[i] + [int(v) for v in row] for i, row in enumerate(cm)],
        title="Confusion matrix")
    metrics = ComponentTable(
        ["metric", "value"],
        [["accuracy", evaluation.accuracy()],
         ["precision", evaluation.precision()],
         ["recall", evaluation.recall()],
         ["f1", evaluation.f1()]],
        title="Metrics")
    with open(path, "w") as f:
        f.write(render_page("Evaluation report", metrics, table))
